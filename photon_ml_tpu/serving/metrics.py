"""Serving observability: one thread-safe accumulator, JSON out.

Counts requests/rows/batches, shed and deadline failures, entity hit-rate,
bucket compiles, and model swaps; keeps a bounded ring of request latencies
for percentile estimates and a running batch-occupancy mean (rows actually
scored / padded bucket rows — the padding waste of the power-of-two
bucketing rule, the serving twin of `RandomEffectDataset.padding_stats`).

`snapshot()` is the JSON surface: the serve CLI dumps it on SIGUSR1 and on
a periodic timer, and `bench.py --serve` records it in BENCH_serve.json.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

import numpy as np


class ServingMetrics:
    """All mutation behind one lock; snapshot() copies then computes."""

    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batched_rows = 0          # rows through device batches
        self.bucket_rows = 0           # padded bucket rows those cost
        self.shed = 0
        self.deadline_exceeded = 0
        self.errors = 0
        self.entity_lookups = 0
        self.entity_hits = 0
        self.bucket_compiles = 0
        self.swaps = 0
        self.rollbacks = 0
        self._latencies = collections.deque(maxlen=latency_window)
        self._queue_wait_sum = 0.0
        self._score_time_sum = 0.0
        self._requests_per_batch_sum = 0

    # -- recording ---------------------------------------------------------

    def observe_request(self, latency_s: float, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows
            self._latencies.append(latency_s)

    def observe_batch(self, *, rows: int, bucket_rows: int,
                      num_requests: int, entity_hits: int,
                      entity_lookups: int, new_compiles: int,
                      queue_wait_s: float, score_s: float) -> None:
        with self._lock:
            self.batches += 1
            self.batched_rows += rows
            self.bucket_rows += bucket_rows
            self._requests_per_batch_sum += num_requests
            self.entity_hits += entity_hits
            self.entity_lookups += entity_lookups
            self.bucket_compiles += new_compiles
            self._queue_wait_sum += queue_wait_s
            self._score_time_sum += score_s

    def observe_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def observe_deadline(self) -> None:
        with self._lock:
            self.deadline_exceeded += 1

    def observe_error(self) -> None:
        with self._lock:
            self.errors += 1

    def observe_swap(self, rollback: bool = False) -> None:
        with self._lock:
            if rollback:
                self.rollbacks += 1
            else:
                self.swaps += 1

    # -- reporting ---------------------------------------------------------

    def snapshot(self, model_version: Optional[str] = None) -> Dict:
        with self._lock:
            lat = np.asarray(self._latencies, np.float64)
            out = {
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "requests_per_batch": round(
                    self._requests_per_batch_sum / self.batches, 3)
                if self.batches else None,
                "batch_occupancy": round(
                    self.batched_rows / self.bucket_rows, 4)
                if self.bucket_rows else None,
                "entity_hit_rate": round(
                    self.entity_hits / self.entity_lookups, 4)
                if self.entity_lookups else None,
                "bucket_compiles": self.bucket_compiles,
                "shed": self.shed,
                "deadline_exceeded": self.deadline_exceeded,
                "errors": self.errors,
                "swaps": self.swaps,
                "rollbacks": self.rollbacks,
                "mean_queue_wait_ms": round(
                    1e3 * self._queue_wait_sum / self.batches, 3)
                if self.batches else None,
                "mean_batch_score_ms": round(
                    1e3 * self._score_time_sum / self.batches, 3)
                if self.batches else None,
            }
        if lat.size:
            out["latency_ms"] = {
                "p50": round(1e3 * float(np.percentile(lat, 50)), 3),
                "p90": round(1e3 * float(np.percentile(lat, 90)), 3),
                "p99": round(1e3 * float(np.percentile(lat, 99)), 3),
                "max": round(1e3 * float(lat.max()), 3),
                "window": int(lat.size),
            }
        else:
            out["latency_ms"] = None
        if model_version is not None:
            out["model_version"] = model_version
        return out
