"""Online scoring subsystem: low-latency request/response GAME inference.

The offline half of this repo (cli.train / cli.score) is batch-oriented;
this package is the serving half of the ROADMAP north star.  Four pieces:

  - `scorer.CompiledScorer` — a GAME model directory loaded into
    device-resident arrays (fixed-effect coefficients, stacked random-effect
    tables with host-side id->row hash maps, MF factors), scoring through
    ONE pre-jitted program per power-of-two batch bucket so no request ever
    compiles after warmup.
  - `batcher.MicroBatcher` — dynamic micro-batching: concurrent score()
    calls coalesce into one padded device call, with max-wait / max-batch
    knobs and load shedding (`Overloaded`, `DeadlineExceeded`).
  - `registry.ModelRegistry` — versioned scorers with zero-downtime hot
    swap and rollback.
  - `service.ScoringService` — the assembled in-process service, with
    `metrics.ServingMetrics` observability (latency percentiles, batch
    occupancy, entity hit-rate, shed counts) and
    ScoringBatchEvent/ModelSwapEvent hooks (utils/events.py).

CLI entrypoint: `python -m photon_ml_tpu.cli.serve`.
"""
from photon_ml_tpu.serving.batcher import (  # noqa: F401
    BatcherConfig, DeadlineExceeded, MicroBatcher, Overloaded, ServingError,
)
from photon_ml_tpu.serving.metrics import ServingMetrics  # noqa: F401
from photon_ml_tpu.serving.registry import ModelRegistry  # noqa: F401
from photon_ml_tpu.serving.scorer import CompiledScorer  # noqa: F401
from photon_ml_tpu.serving.service import (  # noqa: F401
    ScoringService, ServingConfig,
)
