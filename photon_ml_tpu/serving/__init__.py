"""Online scoring subsystem: low-latency request/response GAME inference.

The offline half of this repo (cli.train / cli.score) is batch-oriented;
this package is the serving half of the ROADMAP north star.  Four pieces:

  - `scorer.CompiledScorer` — a GAME model directory loaded into
    device-resident arrays (fixed-effect coefficients, stacked random-effect
    tables with host-side id->row hash maps, MF factors), scoring through
    ONE pre-jitted program per power-of-two batch bucket so no request ever
    compiles after warmup.
  - `batcher.MicroBatcher` — dynamic micro-batching: concurrent score()
    calls coalesce into one padded device call, with max-wait / max-batch
    knobs and load shedding (`Overloaded`, `DeadlineExceeded`).
  - `registry.ModelRegistry` — versioned scorers with zero-downtime hot
    swap, row-level delta swaps (`apply_delta`, the online tier's publish
    path) and delta-aware rollback (exact pre-delta rows restored).
  - `service.ScoringService` — the assembled in-process service, with
    `metrics.ServingMetrics` observability (latency percentiles, batch
    occupancy, entity hit-rate, shed counts, model staleness and online
    feedback-to-publish latency) and ScoringBatchEvent/ModelSwapEvent/
    ModelDeltaEvent hooks (utils/events.py).

The online learning tier on top of this package lives in
photon_ml_tpu/online/ (`ScoringService(updates=...)` / cli.serve
--enable-updates).

CLI entrypoint: `python -m photon_ml_tpu.cli.serve`.
"""
from photon_ml_tpu.serving.batcher import (  # noqa: F401
    BatcherConfig, DeadlineExceeded, MicroBatcher, Overloaded, ServingError,
)
from photon_ml_tpu.serving.metrics import ServingMetrics  # noqa: F401
from photon_ml_tpu.serving.registry import (  # noqa: F401
    ModelRegistry, StaleDeltaError,
)
from photon_ml_tpu.serving.scorer import CompiledScorer  # noqa: F401
from photon_ml_tpu.serving.service import (  # noqa: F401
    ScoringService, ServingConfig,
)
