"""Dynamic micro-batching: concurrent requests -> one padded device call.

Per-request device dispatches waste the accelerator (each launch costs the
same whether it scores 1 row or 1024 — the amortize-launches-over-batches
observation of Snap ML, arXiv:1803.06333).  The batcher holds a thread-safe
queue; a worker thread coalesces whatever arrives within `max_wait_s`
(default 2 ms) up to `max_batch` rows and scores it as ONE call.  Load is
shed explicitly instead of queuing without bound:

  - queue full at submit time       -> `Overloaded` (immediate)
  - per-request deadline passes
    while the request is queued     -> `DeadlineExceeded`

so a saturated service degrades to fast failures, never unbounded latency.
A request already handed to the device when its deadline passes is
completed and returned (the deadline bounds QUEUE wait, the only unbounded
stage).
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from photon_ml_tpu.utils import locktrace


class ServingError(RuntimeError):
    """Base class for explicit serving failures."""


class Overloaded(ServingError):
    """The request queue is at capacity; the request was shed, not queued."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it reached the device."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Coalescing knobs: wait at most `max_wait_s` for co-travellers, never
    exceed `max_batch` rows per device call, shed beyond `max_queue`
    pending requests."""

    max_wait_s: float = 0.002
    max_batch: int = 1024
    max_queue: int = 4096


class _Request:
    __slots__ = ("features", "ids", "n", "deadline", "event", "scores",
                 "error", "enqueue_t")

    def __init__(self, features, ids, n, deadline):
        self.features = features
        self.ids = ids
        self.n = n
        self.deadline = deadline
        self.event = threading.Event()
        self.scores = None
        self.error = None
        self.enqueue_t = time.monotonic()


class MicroBatcher:
    """Request queue + coalescing worker.

    `score_fn(features, ids, num_requests, queue_wait_s)` is called on the
    worker thread with the concatenated batch and must return an object
    with a `.scores` array in row order (serving.scorer.ScoreBatchResult).
    It is resolved per BATCH, so a registry hot swap takes effect at the
    next batch boundary while in-flight batches finish on the old model.
    """

    def __init__(self, score_fn: Callable, config: BatcherConfig = None,
                 on_shed: Optional[Callable[[], None]] = None,
                 on_deadline: Optional[Callable[[], None]] = None):
        self._score_fn = score_fn
        self.config = config or BatcherConfig()
        if self.config.max_batch < 1 or self.config.max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self._on_shed = on_shed
        self._on_deadline = on_deadline
        self._cv = locktrace.tracked(threading.Condition(),
                                     "MicroBatcher._cv")
        self._queue: collections.deque = collections.deque()
        self._open = True
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="photon-serving-batcher")
        self._worker.start()

    # -- client side -------------------------------------------------------

    def score(self, features: Dict[str, np.ndarray],
              ids: Dict[str, np.ndarray], n: int,
              timeout: Optional[float] = None) -> np.ndarray:
        """Block until the batch containing this request is scored.
        `timeout` is the request deadline in seconds (None = no deadline)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        req = _Request(features, ids, n, deadline)
        with self._cv:
            if not self._open:
                raise ServingError("batcher is closed")
            shed = len(self._queue) >= self.config.max_queue
            if not shed:
                self._queue.append(req)
                self._cv.notify()
        if shed:
            # the shed callback runs OUTSIDE the condition: it is
            # arbitrary metrics/listener code, and invoking it under the
            # batcher lock would nest foreign locks inside _cv (a
            # photonlint PH011/PH012 hazard on the hottest serving path)
            if self._on_shed is not None:
                self._on_shed()
            raise Overloaded(
                f"request queue at capacity ({self.config.max_queue} "
                "pending requests)")
        # the worker ALWAYS sets the event (scored, errored, expired, or
        # closed), so an un-set event after deadline + grace means only
        # that the device call itself is still running — keep waiting in
        # grace increments rather than abandoning a result that will come
        while not req.event.wait(
                None if deadline is None
                else max(deadline - time.monotonic(), 0.0) + 30.0):
            pass
        if req.error is not None:
            raise req.error
        return req.scores

    def close(self) -> None:
        with self._cv:
            self._open = False
            self._cv.notify_all()
        self._worker.join(timeout=30.0)

    @property
    def pending(self) -> int:
        with self._cv:
            return len(self._queue)

    # -- worker side -------------------------------------------------------

    def _take_batch(self):
        """Wait for work, hold the coalescing window, pop <= max_batch rows
        (FIFO; a single over-sized request rides alone — the scorer chunks
        it)."""
        cfg = self.config
        with self._cv:
            while self._open and not self._queue:
                self._cv.wait()
            if not self._queue:
                return None  # closed and drained
            first_t = time.monotonic()
            while self._open:
                rows = sum(r.n for r in self._queue)
                remaining = cfg.max_wait_s - (time.monotonic() - first_t)
                if rows >= cfg.max_batch or remaining <= 0:
                    break
                self._cv.wait(remaining)
            batch, rows = [], 0
            while self._queue:
                nxt = self._queue[0]
                if batch and rows + nxt.n > cfg.max_batch:
                    break
                batch.append(self._queue.popleft())
                rows += nxt.n
                if rows >= cfg.max_batch:
                    break
            return batch

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            now = time.monotonic()
            live = []
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    r.error = DeadlineExceeded(
                        f"deadline passed after {now - r.enqueue_t:.4f}s "
                        "in queue")
                    r.event.set()
                    if self._on_deadline is not None:
                        self._on_deadline()
                else:
                    live.append(r)
            if not live:
                continue
            try:
                if len(live) == 1:
                    feats, ids = live[0].features, live[0].ids
                else:
                    feats = {s: np.concatenate(
                        [np.asarray(r.features[s]) for r in live])
                        for s in live[0].features}
                    ids = {t: np.concatenate(
                        [np.asarray(r.ids[t], dtype=object) for r in live])
                        for t in live[0].ids}
                queue_wait = now - min(r.enqueue_t for r in live)
                result = self._score_fn(feats, ids, num_requests=len(live),
                                        queue_wait_s=queue_wait)
                scores = np.asarray(result.scores)
                off = 0
                for r in live:
                    r.scores = scores[off:off + r.n]
                    off += r.n
                    r.event.set()
            except Exception as e:  # propagate to every waiter, keep serving
                for r in live:
                    r.error = e
                    r.event.set()
