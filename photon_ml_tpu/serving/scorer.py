"""Compiled online scorer: a GAME model resident on the device.

The offline scoring path (`GameModel.score_dataset`) builds per-dataset
caches and is shaped for one huge batch; serving needs the transpose —
the MODEL stays resident (fixed-effect coefficient vectors, stacked
random-effect coefficient tables, MF factors, all device arrays built once
at load), and small request batches stream through ONE pre-jitted program
per power-of-two batch bucket.  Related work keeps the model on the
accelerator and amortizes launches over batched requests for exactly this
reason (Snap ML, arXiv:1803.06333; GPU primal learning, arXiv:2008.03433).

Entity identity is resolved host-side: each random-effect coordinate
carries an id->row hash map; ids unseen at training time map to row -1 and
contribute score 0, so such rows fall back to fixed-effect-only scores
exactly like the offline path (reference: the missing-score default,
Evaluator.scala:35-45).

Models past the device budget serve through the tiered entity store
(`store=StoreConfig(...)`): each random-effect table lives in a
photon_ml_tpu.store.TieredEntityStore — a device-resident HOT subset the
bucket programs gather from by slot, a host warm tier, and sealed cold
segments on disk.  A request chunk's misses ride the chunk's own device
transfer as a per-batch staging window (its lanes gather from a second
traced table argument), so a miss never compiles anything or copies the
hot table; promotion into the hot set is amortized in the store.  Online
deltas land in whatever tier a row lives in and feedback for cold
entities promotes them; tiered scores are bit-identical to the
fully-resident scorer's.

Scoring semantics match `GameModel.score_dataset`: the returned value is
the summed margin contribution of every coordinate, WITHOUT offsets or the
inverse link (`mean_prediction` applies the link when callers want means).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry.timings import clock

from photon_ml_tpu.models.game import (
    FactoredRandomEffectModel, FixedEffectModel, GameModel,
    MatrixFactorizationModel, RandomEffectModel,
)
from photon_ml_tpu.ops import losses as L
from photon_ml_tpu.parallel.random_effect import score_by_entity
from photon_ml_tpu.utils.math import ceil_pow2


@dataclasses.dataclass
class ScoreBatchResult:
    """One scored request batch + the stats the metrics accumulator wants."""

    scores: np.ndarray          # [n] margins, request row order
    num_rows: int
    buckets: List[int]          # padded bucket size per device call
    entity_lookups: int         # id resolutions attempted (all RE + MF)
    entity_hits: int            # resolutions that found a trained row
    new_compiles: int           # bucket shapes first seen by this call


def _id_lookup(entity_ids: np.ndarray) -> dict:
    """Host-side id -> table-row hash map (the serving replacement for the
    offline path's per-dataset vocab joins)."""
    return {v: i for i, v in enumerate(np.asarray(entity_ids).tolist())}


@jax.jit
def _scatter_rows(table, rows, values):
    """Row-level delta swap: scatter changed rows into a stacked table.
    Padding lanes carry an out-of-range row index and DROP, so one
    compiled program per (table shape, pow-2 row count) covers every
    delta — steady-state updates trace nothing new."""
    return table.at[rows].set(values, mode="drop")


@jax.jit
def _gather_rows(table, rows):
    """Row gather for delta priors (pad lanes clamp to row 0; callers mask
    them out host-side)."""
    return table[jnp.maximum(rows, 0)]


def _pad_pow2_rows(rows: np.ndarray, values: np.ndarray, num_table_rows: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a row-update set to the next power of two with out-of-range
    (dropped) scatter lanes, so delta row counts map onto a bounded set of
    compiled scatter shapes."""
    k = len(rows)
    pad = int(ceil_pow2(max(k, 1))) - k
    if pad == 0:
        return rows, values
    rows_p = np.concatenate(
        [rows, np.full(pad, num_table_rows, dtype=rows.dtype)])
    values_p = np.concatenate(
        [values, np.zeros((pad, values.shape[1]), values.dtype)])
    return rows_p, values_p


def _resolve_lanes(lookup: dict, ids: np.ndarray) -> np.ndarray:
    return np.fromiter((lookup.get(v, -1) for v in np.asarray(ids).tolist()),
                       dtype=np.int32, count=len(ids))


class CompiledScorer:
    """Device-resident GAME model + bucket-jitted scoring programs.

    `score(features, ids)` takes per-shard feature rows
    (`{shard: [n, d]}`) and per-entity-type raw ids (`{re_type: [n]}`),
    pads each chunk to the smallest power-of-two bucket
    (`utils.math.ceil_pow2`, the same rule training prep buckets with),
    and runs one fused XLA program.  `warmup()` pre-compiles every bucket
    so no request triggers a compile afterwards.
    """

    def __init__(self, model: GameModel, *, max_batch: int = 1024,
                 min_bucket: int = 8, version: Optional[str] = None,
                 store=None, store_dir: Optional[str] = None,
                 shard=None, warm_margins: Optional[bool] = None):
        if max_batch < 1 or min_bucket < 1:
            raise ValueError("max_batch and min_bucket must be >= 1")
        self.model = model
        self.version = version
        # entity-sharded serving (fleet/shards.py): a ShardAssignment
        # makes this scorer hold ONLY its owned slice of every
        # random-effect table (FE/MF coordinates replicate in full), and
        # filter replicated delta/row-state scatters to owned rows
        self.shard = shard
        # margins-program warmup: sharded replicas serve score_margins()
        # on the fan-out path, so they pre-compile it by default
        self.warm_margins = (shard is not None if warm_margins is None
                             else bool(warm_margins))
        self.max_batch = int(ceil_pow2(max_batch))
        self.min_bucket = min(int(ceil_pow2(min_bucket)), self.max_batch)
        self._loss = L.TASK_LOSSES.get(model.task_type)
        # tiered-store serving (photon_ml_tpu/store/): every RE table
        # lives behind a TieredEntityStore instead of fully device-resident
        if store is not None and store_dir is None:
            raise ValueError("store=StoreConfig(...) requires store_dir "
                             "(the cold tier's segment directory)")
        if store is not None and store.overlay_rows < self.max_batch:
            raise ValueError(
                f"store overlay_rows ({store.overlay_rows}) must cover "
                f"the largest scoring chunk (max_batch={self.max_batch}):"
                " a single batch could otherwise miss more distinct rows "
                "than the staging overlay holds")
        self._store_config = store
        self._store_dir = store_dir
        self._stores: Dict[str, object] = {}

        # static program structure (baked into _compute) + device tables
        self._fe_meta: List[Tuple[str, str]] = []          # (name, shard)
        self._re_meta: List[Tuple[str, str, str]] = []     # (name, shard, re_type)
        self._mf_meta: List[Tuple[str, str, str]] = []     # (name, row_t, col_t)
        self._lookups: Dict[str, dict] = {}                # lane key -> id map
        self._table_slot: Dict[str, int] = {}              # RE name -> slot
        self._overlay_slot: Dict[str, int] = {}            # store coord -> slot
        self._entity_ids: Dict[str, np.ndarray] = {}       # RE name -> ids held
        self._shard_row_maps: Dict[str, dict] = {}         # RE name -> full->local
        self._logical_rows: Dict[str, int] = {}            # RE name -> owned rows
        self.shard_rows_dropped = 0   # unowned delta/replay rows filtered
        tables = []
        shard_dims: Dict[str, int] = {}

        def shard_slice(m):
            """A RE coordinate's (entity_ids, table, full->local map) under
            this scorer's shard assignment — owned rows only, ORIGINAL row
            order preserved (so the slice is a pure filter of the full
            table and per-shard audits hash the same bytes on the
            publisher's filtered view and the replica's resident table).
            A shard owning zero entities keeps one never-addressed zero
            row so the gather programs stay well-formed; its logical row
            count is 0 and audits hash the empty slice."""
            ids_full = np.asarray(m.entity_ids)
            table_full = np.asarray(m.global_coefficients())
            if self.shard is None:
                return ids_full, table_full, None, len(ids_full)
            mask = self.shard.spec.owned_mask(ids_full, self.shard.index)
            row_map = {int(full): local for local, full
                       in enumerate(np.nonzero(mask)[0].tolist())}
            ids_own = ids_full[mask]
            table_own = table_full[mask]
            logical = len(ids_own)
            if logical == 0:
                table_own = np.zeros((1, table_full.shape[1]),
                                     table_full.dtype)
            return ids_own, table_own, row_map, logical

        def note_shard(shard, dim, owner):
            prev = shard_dims.setdefault(shard, int(dim))
            if prev != int(dim):
                raise ValueError(
                    f"coordinate {owner!r} scores shard {shard!r} at width "
                    f"{int(dim)} but another coordinate uses width {prev}")

        for name, m in model.coordinates.items():
            if isinstance(m, FixedEffectModel):
                w = jnp.asarray(m.glm.coefficients.means)
                note_shard(m.feature_shard, w.shape[-1], name)
                self._fe_meta.append((name, m.feature_shard))
                tables.append(w)
            elif isinstance(m, (RandomEffectModel, FactoredRandomEffectModel)):
                # stacked per-entity table in the ORIGINAL shard space:
                # projected/factored coordinates materialize P^T c once at
                # load so serving is a single gather + row dot per request
                own_ids, own_table, row_map, logical = shard_slice(m)
                self._entity_ids[name] = own_ids
                self._logical_rows[name] = logical
                if row_map is not None:
                    self._shard_row_maps[name] = row_map
                if store is not None:
                    import os
                    from photon_ml_tpu.store import TieredEntityStore
                    table_np = own_table
                    note_shard(m.feature_shard, table_np.shape[-1], name)
                    st = TieredEntityStore.create(
                        os.path.join(store_dir, name.replace("/", "_")),
                        table_np, store,
                        entity_ids=own_ids if logical else
                        np.asarray(["\0__shard_pad__"], dtype=object),
                        name=name)
                    self._stores[name] = st
                    self._re_meta.append((name, m.feature_shard,
                                          m.random_effect_type))
                    self._table_slot[name] = len(tables)
                    tables.append(st.table())
                    # the staging window rides as its own traced table:
                    # a batch's missed-row values score out of it (built
                    # host-side per batch, shipped with the batch's own
                    # transfer) while promotion into the main hot table
                    # stays amortized.  The entry here is a placeholder
                    # pinning the static [overlay_rows, d] shape.
                    self._overlay_slot[name] = len(tables)
                    tables.append(jnp.zeros((st.overlay_rows, st.dim),
                                            st.dtype))
                else:
                    table = jnp.asarray(own_table)
                    note_shard(m.feature_shard, table.shape[-1], name)
                    self._re_meta.append((name, m.feature_shard,
                                          m.random_effect_type))
                    self._lookups[name] = (_id_lookup(own_ids) if logical
                                           else {})
                    self._table_slot[name] = len(tables)
                    tables.append(table)
            elif isinstance(m, MatrixFactorizationModel):
                self._mf_meta.append((name, m.row_effect_type,
                                      m.col_effect_type))
                self._lookups[name + "/row"] = _id_lookup(m.row_ids)
                self._lookups[name + "/col"] = _id_lookup(m.col_ids)
                tables.append(jnp.asarray(m.row_factors))
                tables.append(jnp.asarray(m.col_factors))
            else:
                raise TypeError(f"unknown coordinate model type {type(m)}")
        if not tables:
            raise ValueError("model has no coordinates to serve")
        # deliberately lock-free: delta publishers replace the WHOLE tuple
        # (never mutate in place) and scoring threads read it once per
        # batch — atomic publish at batch granularity
        self._tables = tuple(tables)  # photonlint: guarded-by=atomic
        self.feature_shards: Dict[str, int] = shard_dims
        self.entity_types = sorted(
            {t for _, _, t in self._re_meta}
            | {t for _, r, c in self._mf_meta for t in (r, c)})
        self._dtype = (jnp.result_type(*self._tables) if self._tables
                       else jnp.float32)
        # one jitted program, cached per bucket shape; tables are traced
        # ARGUMENTS (not closed-over constants), so a same-shape hot swap
        # reuses every compiled bucket program
        self._program = jax.jit(self._compute)
        # the fan-out twin: same contributions, returned per coordinate
        # instead of folded — what sharded replicas serve to the front
        self._program_margins = jax.jit(self._compute_margins)
        self._seen_buckets: set = set()
        self.bucket_compiles = 0
        self.warmup_s = 0.0
        self.warmed = False
        # online-update version vector: seq of the newest applied delta
        # (0 = pristine full-model load) + lifetime apply/revert counts
        self.delta_seq = 0
        self.deltas_applied = 0
        self.deltas_reverted = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def from_model_dir(cls, model_dir: str, *, max_batch: int = 1024,
                       min_bucket: int = 8, version: Optional[str] = None,
                       warmup: bool = True, store=None,
                       store_dir: Optional[str] = None, shard=None,
                       warm_margins: Optional[bool] = None
                       ) -> "CompiledScorer":
        from photon_ml_tpu.models.io import load_game_model
        model, _config = load_game_model(model_dir)
        scorer = cls(model, max_batch=max_batch, min_bucket=min_bucket,
                     version=version, store=store, store_dir=store_dir,
                     shard=shard, warm_margins=warm_margins)
        if warmup:
            scorer.warmup()
        return scorer

    def bucket_sizes(self) -> List[int]:
        out, b = [], self.min_bucket
        while b < self.max_batch:
            out.append(b)
            b <<= 1
        out.append(self.max_batch)
        return out

    def _lane_names(self) -> List[str]:
        names = []
        for name, _, _ in self._re_meta:
            names.append(name)
            if name in self._stores:
                names.append(name + "@stage")
        names += [name + side for name, _, _ in self._mf_meta
                  for side in ("/row", "/col")]
        return names

    def warmup(self) -> float:
        """Compile every bucket program now, so no request ever does.
        Store-backed tables also pre-compile their promotion/delta
        scatter shapes, so steady-state misses trace nothing either."""
        t0 = clock()
        with telemetry.span("serve_warmup", version=self.version):
            for st in self._stores.values():
                st.warmup()
            for b in self.bucket_sizes():
                xs = {s: np.zeros((b, d), np.float64)
                      for s, d in self.feature_shards.items()}
                lanes = {k: np.full(b, -1, np.int32)
                         for k in self._lane_names()}
                jax.block_until_ready(self._run_bucket(xs, lanes, b))
                if self.warm_margins:
                    jax.block_until_ready(
                        self._run_bucket(xs, lanes, b, margins=True))
        self.warmup_s = clock() - t0
        self.warmed = True
        return self.warmup_s

    # -- the device program ------------------------------------------------

    def _compute(self, tables, xs, lanes):
        """Summed coordinate margins for one padded bucket — ONE fused
        program (FE matvecs + RE gather-dots + MF factor dots), mirroring
        GameModel.score_dataset coordinate by coordinate."""
        i = 0
        total = None

        def add(z):
            nonlocal total
            total = z if total is None else total + z

        for _name, shard in self._fe_meta:
            w = tables[i]; i += 1
            add(xs[shard] @ w)
        for name, shard, _re_type in self._re_meta:
            table = tables[i]; i += 1
            add(score_by_entity(table, xs[shard], lanes[name]))
            if name in self._stores:
                # tiered coordinate: a row lives in EXACTLY one of the
                # main hot table / staging overlay (the other lane is
                # -1 -> contributes 0), so the sum is the full margin
                overlay = tables[i]; i += 1
                add(score_by_entity(overlay, xs[shard],
                                    lanes[name + "@stage"]))
        for name, _row_t, _col_t in self._mf_meta:
            rf, cf = tables[i], tables[i + 1]; i += 2
            rl, cl = lanes[name + "/row"], lanes[name + "/col"]
            ok = (rl >= 0) & (cl >= 0)
            rfa = rf[jnp.maximum(rl, 0)]
            cfa = cf[jnp.maximum(cl, 0)]
            add(jnp.where(ok, jnp.sum(rfa * cfa, axis=-1), 0.0))
        return total

    def coordinate_meta(self) -> List[Dict[str, str]]:
        """The coordinate fold order as data — one ordered entry per
        margin `_compute` adds (FE, then RE, then MF, each in model
        order).  This is the merge contract of entity-sharded fan-out
        scoring: the front re-folds per-coordinate margins host-side in
        EXACTLY this order (fleet/shards.py merge_margins), which is what
        makes merged scores bit-identical to a monolithic replica's."""
        out: List[Dict[str, str]] = []
        for name, shard in self._fe_meta:
            out.append({"name": name, "kind": "fixed",
                        "feature_shard": shard})
        for name, shard, re_type in self._re_meta:
            out.append({"name": name, "kind": "random",
                        "feature_shard": shard, "entity_type": re_type})
        for name, row_t, col_t in self._mf_meta:
            out.append({"name": name, "kind": "matrix",
                        "row_type": row_t, "col_type": col_t})
        return out

    def _compute_margins(self, tables, xs, lanes):
        """Per-coordinate margins for one padded bucket, in
        `coordinate_meta()` order — the same contribution terms `_compute`
        folds, returned unfolded.  A tiered coordinate's hot-table and
        staging-window contributions combine into ONE margin here (a row
        lives in exactly one of the two, the other lane is -1 -> 0.0), so
        the margin is the coordinate's full contribution regardless of
        tiering — and the merge fold stays one add per coordinate,
        matching the fully-resident monolithic chain."""
        i = 0
        margins = []
        for _name, shard in self._fe_meta:
            w = tables[i]; i += 1
            margins.append(xs[shard] @ w)
        for name, shard, _re_type in self._re_meta:
            table = tables[i]; i += 1
            z = score_by_entity(table, xs[shard], lanes[name])
            if name in self._stores:
                overlay = tables[i]; i += 1
                z = z + score_by_entity(overlay, xs[shard],
                                        lanes[name + "@stage"])
            margins.append(z)
        for name, _row_t, _col_t in self._mf_meta:
            rf, cf = tables[i], tables[i + 1]; i += 2
            rl, cl = lanes[name + "/row"], lanes[name + "/col"]
            ok = (rl >= 0) & (cl >= 0)
            rfa = rf[jnp.maximum(rl, 0)]
            cfa = cf[jnp.maximum(cl, 0)]
            margins.append(jnp.where(ok, jnp.sum(rfa * cfa, axis=-1), 0.0))
        return tuple(margins)

    def _run_bucket(self, xs, lanes, bucket: int, store_tables=None,
                    margins: bool = False):
        if bucket not in self._seen_buckets:
            self._seen_buckets.add(bucket)
            self.bucket_compiles += 1
        # ONE batched host->device transfer for every feature shard,
        # lane array, and staged-miss window (per-array dispatch
        # overhead dominates small-batch serving latency on weak hosts;
        # the dtype cast stays host-side)
        np_dtype = np.dtype(self._dtype)
        windows = {name: w for name, (_t, w) in store_tables.items()} \
            if store_tables else {}
        xs, lanes, windows = jax.device_put((
            {s: np.asarray(x, np_dtype) for s, x in xs.items()},
            {k: np.asarray(v) for k, v in lanes.items()},
            windows))
        tables = self._tables
        if store_tables:
            # tiered mode: each chunk scores against the EXACT hot-table
            # snapshot its slots were resolved into (batch-granularity
            # consistency — a concurrent promotion replaces the store's
            # table functionally, never mutating this snapshot) plus its
            # own private staging window
            t = list(tables)
            for name, (table, _w) in store_tables.items():
                t[self._table_slot[name]] = table
                t[self._overlay_slot[name]] = windows[name]
            tables = tuple(t)
        if margins:
            return self._program_margins(tables, xs, lanes)
        return self._program(tables, xs, lanes)

    # -- online row-level updates ------------------------------------------

    def updatable_coordinates(self) -> List[Tuple[str, str, str]]:
        """(name, feature_shard, re_type) of every coordinate whose stacked
        table accepts row-level delta swaps (plain + factored random
        effects; MF factor pairs are not online-updatable — prefer a full
        refit there)."""
        return list(self._re_meta)

    def re_table(self, name: str) -> jax.Array:
        """The device-resident stacked table of one RE coordinate
        (original shard space — what apply_delta scatters into; in tiered
        mode this is the HOT subset, addressed by slot)."""
        st = self._stores.get(name)
        if st is not None:
            return st.table()
        return self._tables[self._table_slot[name]]

    def entity_row(self, name: str, entity_id) -> int:
        """Table row of a raw entity id under coordinate `name`
        (-1 = unseen at training time; such entities cannot be
        online-updated — the table has no row to anchor at)."""
        st = self._stores.get(name)
        if st is not None:
            return st.resolve_one(entity_id)
        return self._lookups[name].get(entity_id, -1)

    def entity_store(self, name: str):
        """The TieredEntityStore behind one coordinate (None when the
        table is fully device-resident)."""
        return self._stores.get(name)

    @property
    def tiered(self) -> bool:
        return bool(self._stores)

    def gather_rows(self, name: str, rows: np.ndarray) -> jax.Array:
        """Gather of table rows (delta priors / anchors).  Tiered mode
        reads the authoritative warm/cold bytes host-side — bit-exact
        with what the hot tier serves."""
        st = self._stores.get(name)
        if st is not None:
            return jnp.asarray(st.gather_rows(np.asarray(rows, np.int64)))
        return _gather_rows(self.re_table(name),
                            jnp.asarray(np.asarray(rows, np.int64)))

    def _filter_shard_rows(self, name: str, rows: np.ndarray,
                           values: np.ndarray
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """The shard-filtering chokepoint of EVERY row write: replicated
        deltas, rollback row-state replays, and snapshot bootstraps all
        carry FULL-model row indices; a sharded scorer keeps only its
        owned rows, remapped to its local (filtered) table space.
        Unowned rows drop silently — their owner's replica applies them —
        and are counted in `shard_rows_dropped`."""
        row_map = self._shard_row_maps.get(name)
        if row_map is None:
            return rows, values
        keep = [i for i, r in enumerate(rows.tolist()) if int(r) in row_map]
        self.shard_rows_dropped += len(rows) - len(keep)
        local = np.asarray([row_map[int(rows[i])] for i in keep], np.int64)
        return local, values[keep]

    def _scatter_coordinate(self, name: str, rows: np.ndarray,
                            values: np.ndarray,
                            promote: bool = False) -> None:
        slot = self._table_slot.get(name)
        if slot is None:
            known = sorted(self._table_slot)
            raise KeyError(f"coordinate {name!r} has no online-updatable "
                           f"table (updatable: {known})")
        rows = np.asarray(rows, np.int64)
        values = np.asarray(values)
        rows, values = self._filter_shard_rows(name, rows, values)
        if len(rows) == 0 and self.shard is not None:
            return  # this shard owns none of the delta's rows
        st = self._stores.get(name)
        if st is not None:
            # tiered mode: the delta lands in whatever tier each row
            # lives in (warm always, hot write-through for resident rows,
            # promote=True pulls cold rows hot — the feedback path)
            if values.shape != (len(rows), st.dim):
                raise ValueError(
                    f"delta values for {name!r} must be [{len(rows)}, "
                    f"{st.dim}], got {values.shape}")
            st.update_rows(rows, values, promote=promote)
            return
        table = self._tables[slot]
        if values.shape != (len(rows), table.shape[1]):
            raise ValueError(
                f"delta values for {name!r} must be [{len(rows)}, "
                f"{table.shape[1]}], got {values.shape}")
        if len(rows) and int(rows.max()) >= table.shape[0]:
            raise ValueError(
                f"delta row {int(rows.max())} out of range for {name!r} "
                f"(table has {table.shape[0]} rows)")
        rows_p, values_p = _pad_pow2_rows(rows, values, table.shape[0])
        new_table = _scatter_rows(table, jnp.asarray(rows_p),
                                  jnp.asarray(values_p, table.dtype))
        tables = list(self._tables)
        tables[slot] = new_table
        # one atomic tuple swap: a concurrent score() batch reads either
        # the old or the new tuple — batch-granularity consistency, same
        # contract as a full-model hot swap
        self._tables = tuple(tables)

    def scatter_rows(self, name: str, rows: np.ndarray,
                     values: np.ndarray) -> None:
        """Scatter raw row values into one coordinate's live table (the
        replication layer's replay primitive: rollback records and
        snapshot bootstraps carry explicit row states rather than
        ModelDeltas).  Callers serialize through the registry lock, same
        contract as apply_delta."""
        self._scatter_coordinate(name, rows, values)

    def warmup_delta(self, max_rows: int = 64) -> float:
        """Pre-compile the delta scatter programs for every pow-2 row
        count up to `max_rows` on every updatable table — the replica
        twin of OnlineUpdater.warmup's scatter block, so steady-state
        delta REPLAY traces nothing (a follower replica has no updater
        to warm these for it)."""
        t0 = clock()
        with telemetry.span("replica_delta_warmup", version=self.version):
            for name, _shard, _re_type in self.updatable_coordinates():
                st = self._stores.get(name)
                if st is not None:
                    # tiered tables replay deltas through the store's own
                    # pre-jitted scatter shapes
                    if not st.warmed:
                        st.warmup()
                    continue
                table = self.re_table(name)
                k = 1
                bound = int(ceil_pow2(max(max_rows, 1)))
                while k <= bound:
                    rows = np.arange(min(k, table.shape[0]), dtype=np.int64)
                    vals = np.zeros((len(rows), table.shape[1]))
                    rows_p, vals_p = _pad_pow2_rows(rows, vals,
                                                    table.shape[0])
                    # result discarded: the live table is never touched
                    jax.block_until_ready(_scatter_rows(
                        table, jnp.asarray(rows_p),
                        jnp.asarray(vals_p, table.dtype)))
                    k <<= 1
        return clock() - t0

    def table_hashes(self):  # photonlint: flush-point -- audit endpoint: one deliberate full-table readback per call, never on the scoring path
        """sha256 of every device table's exact byte content, keyed by
        coordinate lane (MF factor pairs hash as name/row + name/col).
        The fleet audit primitive: two replicas whose version vectors AND
        table hashes agree converged bit-identically."""
        import hashlib
        i = 0
        out: Dict[str, str] = {}
        for name, _shard in self._fe_meta:
            out[name] = hashlib.sha256(
                np.ascontiguousarray(np.asarray(self._tables[i]))
                .tobytes()).hexdigest()
            i += 1
        for name, _shard, _re_type in self._re_meta:
            st = self._stores.get(name)
            if st is not None:
                # tiered mode hashes the LOGICAL table (cold + warm
                # overlay): two replicas whose tiering histories differ
                # but whose row values agree hash identically
                rows_np = np.asarray(st.full_table())
            else:
                rows_np = np.asarray(self._tables[i])
            if self.shard is not None:
                # sharded mode hashes the OWNED slice (a zero-owned
                # shard's never-addressed pad row is excluded), so the
                # hash equals the publisher's shard_table_hashes() of
                # the same filtered rows
                rows_np = rows_np[:self._logical_rows[name]]
            out[name] = hashlib.sha256(
                np.ascontiguousarray(rows_np).tobytes()).hexdigest()
            i += 2 if st is not None else 1
        for name, _row_t, _col_t in self._mf_meta:
            for side in ("/row", "/col"):
                out[name + side] = hashlib.sha256(
                    np.ascontiguousarray(np.asarray(self._tables[i]))
                    .tobytes()).hexdigest()
                i += 1
        return out

    def apply_delta(self, delta) -> None:
        """Scatter a ModelDelta's changed rows into the live tables.
        Callers serialize through the registry lock; scoring threads need
        no lock (the table tuple swap is atomic, and the compiled bucket
        programs take tables as traced ARGUMENTS, so no re-trace).
        Tiered tables land the rows in whatever tier they live in, and
        PROMOTE cold rows hot — an entity the traffic cares enough about
        to send feedback for belongs in the hot set."""
        for name, cd in delta.coordinates.items():
            self._scatter_coordinate(name, cd.rows, cd.values,
                                     promote=True)
        self.delta_seq = delta.seq
        self.deltas_applied += 1

    def revert_delta(self, delta) -> None:
        """Scatter a delta's pre-delta rows back (exact rollback: restores
        the bit pattern the rows had before apply_delta — in tiered mode
        across every tier the delta touched)."""
        for name, cd in delta.coordinates.items():
            self._scatter_coordinate(name, cd.rows, cd.prior)
        self.delta_seq = delta.seq - 1
        self.deltas_reverted += 1

    # -- tiered-store observability ----------------------------------------

    def store_totals(self) -> Dict[str, int]:
        """Cumulative tier counters summed over every store-backed
        coordinate (the ServingMetrics probe; all zeros when fully
        resident)."""
        from photon_ml_tpu.store.entity import store_totals
        return store_totals(self._stores)

    def store_health(self) -> Optional[Dict]:
        """Per-coordinate residency + the aggregate hot hit rate for
        /healthz (None when fully resident)."""
        if not self._stores:
            return None
        totals = self.store_totals()
        lookups = (totals["hot_hits"] + totals["warm_hits"]
                   + totals["cold_misses"])
        return {
            "hit_rate": (round(totals["hot_hits"] / lookups, 4)
                         if lookups else None),
            "promotions": totals["promotions"],
            "spills": totals["spills"],
            "coordinates": {name: st.residency()
                            for name, st in self._stores.items()},
        }

    def flush_stores(self) -> int:
        """Durably spill every dirty warm segment (shutdown/seal hook).
        Returns segments written."""
        return sum(st.flush() for st in self._stores.values())

    # -- request scoring ---------------------------------------------------

    def validate_request(self, features: Dict[str, np.ndarray],
                         ids: Dict[str, np.ndarray]) -> int:
        """Shape/coverage check -> the request's row count.  Raised errors
        are per-request (the batcher propagates them to one caller, not the
        whole batch)."""
        missing = sorted(set(self.feature_shards) - set(features))
        if missing:
            raise ValueError(f"request is missing feature shard(s) {missing}"
                             f" (model scores {sorted(self.feature_shards)})")
        n = None
        for shard, want in self.feature_shards.items():
            x = np.asarray(features[shard])
            if x.ndim != 2 or x.shape[1] != want:
                raise ValueError(
                    f"feature shard {shard!r} must be [n, {want}], got "
                    f"shape {x.shape}")
            if n is None:
                n = x.shape[0]
            elif x.shape[0] != n:
                raise ValueError(
                    f"feature shard {shard!r} has {x.shape[0]} rows; other "
                    f"shards have {n}")
        missing_ids = sorted(set(self.entity_types) - set(ids or {}))
        if missing_ids:
            raise ValueError(
                f"request is missing entity id column(s) {missing_ids} "
                f"(model has random effects over {self.entity_types})")
        for t in self.entity_types:
            col = np.asarray(ids[t])
            if n is None:
                n = len(col)
            if col.shape != (n,):
                raise ValueError(
                    f"id column {t!r} must be [{n}], got shape {col.shape}")
        if n is None or n == 0:
            raise ValueError("empty request")
        return n

    def _lanes_for_chunk(self, ids, lo, hi):
        lanes, hits, lookups = {}, 0, 0
        store_tables = {}
        for name, _shard, re_type in self._re_meta:
            col = np.asarray(ids[re_type])[lo:hi]
            st = self._stores.get(name)
            if st is not None:
                # tiered mode: resolve ids -> global rows, then stage the
                # chunk's misses into the per-batch staging window
                # (promotion into the main table is amortized); lanes
                # are SLOTS into the returned snapshot/window
                rows = st.resolve(col)
                slots, stage, table, staged_vals = st.lookup_slots(rows)
                window = np.zeros((st.overlay_rows, st.dim),
                                  np.dtype(st.dtype))
                window[: len(staged_vals)] = staged_vals
                lanes[name] = slots
                lanes[name + "@stage"] = stage
                store_tables[name] = (table, window)
                hits += int((rows >= 0).sum()); lookups += len(rows)
                continue
            ln = _resolve_lanes(self._lookups[name], col)
            lanes[name] = ln
            hits += int((ln >= 0).sum()); lookups += len(ln)
        for name, row_t, col_t in self._mf_meta:
            for side, t in (("/row", row_t), ("/col", col_t)):
                ln = _resolve_lanes(self._lookups[name + side],
                                    np.asarray(ids[t])[lo:hi])
                lanes[name + side] = ln
                hits += int((ln >= 0).sum()); lookups += len(ln)
        return lanes, hits, lookups, store_tables

    def score(self, features: Dict[str, np.ndarray],
              ids: Optional[Dict[str, np.ndarray]] = None,
              ) -> ScoreBatchResult:
        """Margins for a request batch of any size (chunked at max_batch)."""
        ids = ids or {}
        n = self.validate_request(features, ids)
        out = np.empty(n, np.float64)
        buckets: List[int] = []
        hits = lookups = 0
        compiles0 = self.bucket_compiles
        for lo in range(0, n, self.max_batch):
            hi = min(lo + self.max_batch, n)
            m = hi - lo
            bucket = min(max(int(ceil_pow2(m)), self.min_bucket),
                         self.max_batch)
            pad = bucket - m
            xs = {}
            for shard in self.feature_shards:
                x = np.asarray(features[shard])[lo:hi]
                xs[shard] = (x if pad == 0 else
                             np.pad(x, ((0, pad), (0, 0))))
            lanes, h, lk, store_tables = self._lanes_for_chunk(ids, lo, hi)
            if pad:
                lanes = {k: np.pad(v, (0, pad), constant_values=-1)
                         for k, v in lanes.items()}
            hits += h; lookups += lk
            buckets.append(bucket)
            z = self._run_bucket(xs, lanes, bucket,
                                 store_tables=store_tables)
            out[lo:hi] = np.asarray(z)[:m]
        return ScoreBatchResult(
            scores=out, num_rows=n, buckets=buckets,
            entity_lookups=lookups, entity_hits=hits,
            new_compiles=self.bucket_compiles - compiles0)

    def score_margins(self, features: Dict[str, np.ndarray],
                      ids: Optional[Dict[str, np.ndarray]] = None,
                      ) -> Dict[str, np.ndarray]:
        """Per-coordinate margins for a request batch (chunked at
        max_batch like `score`), keyed by coordinate name in
        `coordinate_meta()` order — one sharded replica's leg of a
        fan-out request.  Margins keep the device program's COMPUTE
        dtype (the merge fold must run in it to reproduce the on-device
        add chain bit-for-bit; `score` casts to f64 only at the end).
        Unowned/unseen entities resolve to lane -1 and contribute
        exactly 0.0, so the merge can fold any leg's margin for a
        coordinate the leg does not own without perturbing bits."""
        ids = ids or {}
        n = self.validate_request(features, ids)
        meta = self.coordinate_meta()
        out = {m["name"]: np.empty(n, np.dtype(self._dtype)) for m in meta}
        for lo in range(0, n, self.max_batch):
            hi = min(lo + self.max_batch, n)
            m = hi - lo
            bucket = min(max(int(ceil_pow2(m)), self.min_bucket),
                         self.max_batch)
            pad = bucket - m
            xs = {}
            for shard in self.feature_shards:
                x = np.asarray(features[shard])[lo:hi]
                xs[shard] = (x if pad == 0 else
                             np.pad(x, ((0, pad), (0, 0))))
            lanes, _h, _lk, store_tables = self._lanes_for_chunk(ids, lo, hi)
            if pad:
                lanes = {k: np.pad(v, (0, pad), constant_values=-1)
                         for k, v in lanes.items()}
            margins = self._run_bucket(xs, lanes, bucket,
                                       store_tables=store_tables,
                                       margins=True)
            for cm, z in zip(meta, margins):
                out[cm["name"]][lo:hi] = np.asarray(z)[:m]
        return out

    # -- entity-sharded serving (fleet/shards.py) --------------------------

    def shard_info(self) -> Optional[Dict[str, object]]:
        """This scorer's shard identity + owned-row counts (the /healthz
        and probe surface the front groups replicas by); None when the
        scorer holds the full model."""
        if self.shard is None:
            return None
        return {**self.shard.to_dict(),
                "owned_rows": {name: self._logical_rows[name]
                               for name, _s, _t in self._re_meta},
                "rows_dropped": self.shard_rows_dropped}

    def shard_table_hashes(self, spec, shard_index: int) -> Dict[str, str]:
        """The per-shard audit on a FULL (publisher) scorer: sha256 of
        every lane's rows FILTERED to `shard_index`'s owned entities
        (original row order) — exactly the bytes a converged shard
        replica's `table_hashes()` reports, since its resident table IS
        that filtered slice.  FE/MF lanes replicate in full and hash
        unfiltered."""
        import hashlib
        if self.shard is not None:
            raise ValueError("shard_table_hashes audits the FULL model; "
                             "this scorer already holds only shard "
                             f"{self.shard.index}")
        full = self.table_hashes()
        out: Dict[str, str] = {}
        for name, _shard in self._fe_meta:
            out[name] = full[name]
        for name, _shard, _re_type in self._re_meta:
            st = self._stores.get(name)
            table = (np.asarray(st.full_table()) if st is not None
                     else np.asarray(self._tables[self._table_slot[name]]))
            mask = spec.owned_mask(self._entity_ids[name], shard_index)
            out[name] = hashlib.sha256(
                np.ascontiguousarray(table[mask]).tobytes()).hexdigest()
        for name, _row_t, _col_t in self._mf_meta:
            for side in ("/row", "/col"):
                out[name + side] = full[name + side]
        return out

    def mean_prediction(self, scores: np.ndarray,
                        offsets: Optional[np.ndarray] = None) -> np.ndarray:
        """Inverse link over margins (+ offsets), like GameModel.predict."""
        if self._loss is None:
            raise ValueError(
                f"task {self.model.task_type!r} has no mean function")
        z = np.asarray(scores, np.float64)
        if offsets is not None:
            z = z + np.asarray(offsets, np.float64)
        return np.asarray(self._loss.mean(jnp.asarray(z)))

    def requests_from_dataset(self, dataset, rows: np.ndarray
                              ) -> Tuple[Dict[str, np.ndarray],
                                         Dict[str, np.ndarray]]:
        """Slice a GameDataset into (features, ids) request form — raw ids
        recovered through the dataset vocab; rows whose entity index is -1
        get a sentinel id no model contains (they stay fixed-effect-only).
        Sparse shards densify per request slice (serving requests are
        small dense rows by construction)."""
        def slice_rows(x):
            if hasattr(x, "tocsr"):  # scipy sparse shard
                return np.asarray(x.tocsr()[rows].todense())
            return np.asarray(x)[rows]

        feats = {s: slice_rows(dataset.feature_shards[s])
                 for s in self.feature_shards}
        ids = {}
        for t in self.entity_types:
            idx = np.asarray(dataset.entity_indices[t])[rows]
            vocab = np.asarray(dataset.entity_vocabs[t], dtype=object)
            raw = vocab[np.maximum(idx, 0)].copy()
            raw[idx < 0] = "\0__unseen__"
            ids[t] = raw
        return feats, ids
