"""Compiled online scorer: a GAME model resident on the device.

The offline scoring path (`GameModel.score_dataset`) builds per-dataset
caches and is shaped for one huge batch; serving needs the transpose —
the MODEL stays resident (fixed-effect coefficient vectors, stacked
random-effect coefficient tables, MF factors, all device arrays built once
at load), and small request batches stream through ONE pre-jitted program
per power-of-two batch bucket.  Related work keeps the model on the
accelerator and amortizes launches over batched requests for exactly this
reason (Snap ML, arXiv:1803.06333; GPU primal learning, arXiv:2008.03433).

Entity identity is resolved host-side: each random-effect coordinate
carries an id->row hash map; ids unseen at training time map to row -1 and
contribute score 0, so such rows fall back to fixed-effect-only scores
exactly like the offline path (reference: the missing-score default,
Evaluator.scala:35-45).

Scoring semantics match `GameModel.score_dataset`: the returned value is
the summed margin contribution of every coordinate, WITHOUT offsets or the
inverse link (`mean_prediction` applies the link when callers want means).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry.timings import clock

from photon_ml_tpu.models.game import (
    FactoredRandomEffectModel, FixedEffectModel, GameModel,
    MatrixFactorizationModel, RandomEffectModel,
)
from photon_ml_tpu.ops import losses as L
from photon_ml_tpu.parallel.random_effect import score_by_entity
from photon_ml_tpu.utils.math import ceil_pow2


@dataclasses.dataclass
class ScoreBatchResult:
    """One scored request batch + the stats the metrics accumulator wants."""

    scores: np.ndarray          # [n] margins, request row order
    num_rows: int
    buckets: List[int]          # padded bucket size per device call
    entity_lookups: int         # id resolutions attempted (all RE + MF)
    entity_hits: int            # resolutions that found a trained row
    new_compiles: int           # bucket shapes first seen by this call


def _id_lookup(entity_ids: np.ndarray) -> dict:
    """Host-side id -> table-row hash map (the serving replacement for the
    offline path's per-dataset vocab joins)."""
    return {v: i for i, v in enumerate(np.asarray(entity_ids).tolist())}


@jax.jit
def _scatter_rows(table, rows, values):
    """Row-level delta swap: scatter changed rows into a stacked table.
    Padding lanes carry an out-of-range row index and DROP, so one
    compiled program per (table shape, pow-2 row count) covers every
    delta — steady-state updates trace nothing new."""
    return table.at[rows].set(values, mode="drop")


@jax.jit
def _gather_rows(table, rows):
    """Row gather for delta priors (pad lanes clamp to row 0; callers mask
    them out host-side)."""
    return table[jnp.maximum(rows, 0)]


def _pad_pow2_rows(rows: np.ndarray, values: np.ndarray, num_table_rows: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a row-update set to the next power of two with out-of-range
    (dropped) scatter lanes, so delta row counts map onto a bounded set of
    compiled scatter shapes."""
    k = len(rows)
    pad = int(ceil_pow2(max(k, 1))) - k
    if pad == 0:
        return rows, values
    rows_p = np.concatenate(
        [rows, np.full(pad, num_table_rows, dtype=rows.dtype)])
    values_p = np.concatenate(
        [values, np.zeros((pad, values.shape[1]), values.dtype)])
    return rows_p, values_p


def _resolve_lanes(lookup: dict, ids: np.ndarray) -> np.ndarray:
    return np.fromiter((lookup.get(v, -1) for v in np.asarray(ids).tolist()),
                       dtype=np.int32, count=len(ids))


class CompiledScorer:
    """Device-resident GAME model + bucket-jitted scoring programs.

    `score(features, ids)` takes per-shard feature rows
    (`{shard: [n, d]}`) and per-entity-type raw ids (`{re_type: [n]}`),
    pads each chunk to the smallest power-of-two bucket
    (`utils.math.ceil_pow2`, the same rule training prep buckets with),
    and runs one fused XLA program.  `warmup()` pre-compiles every bucket
    so no request triggers a compile afterwards.
    """

    def __init__(self, model: GameModel, *, max_batch: int = 1024,
                 min_bucket: int = 8, version: Optional[str] = None):
        if max_batch < 1 or min_bucket < 1:
            raise ValueError("max_batch and min_bucket must be >= 1")
        self.model = model
        self.version = version
        self.max_batch = int(ceil_pow2(max_batch))
        self.min_bucket = min(int(ceil_pow2(min_bucket)), self.max_batch)
        self._loss = L.TASK_LOSSES.get(model.task_type)

        # static program structure (baked into _compute) + device tables
        self._fe_meta: List[Tuple[str, str]] = []          # (name, shard)
        self._re_meta: List[Tuple[str, str, str]] = []     # (name, shard, re_type)
        self._mf_meta: List[Tuple[str, str, str]] = []     # (name, row_t, col_t)
        self._lookups: Dict[str, dict] = {}                # lane key -> id map
        self._table_slot: Dict[str, int] = {}              # RE name -> slot
        tables = []
        shard_dims: Dict[str, int] = {}

        def note_shard(shard, dim, owner):
            prev = shard_dims.setdefault(shard, int(dim))
            if prev != int(dim):
                raise ValueError(
                    f"coordinate {owner!r} scores shard {shard!r} at width "
                    f"{int(dim)} but another coordinate uses width {prev}")

        for name, m in model.coordinates.items():
            if isinstance(m, FixedEffectModel):
                w = jnp.asarray(m.glm.coefficients.means)
                note_shard(m.feature_shard, w.shape[-1], name)
                self._fe_meta.append((name, m.feature_shard))
                tables.append(w)
            elif isinstance(m, (RandomEffectModel, FactoredRandomEffectModel)):
                # stacked per-entity table in the ORIGINAL shard space:
                # projected/factored coordinates materialize P^T c once at
                # load so serving is a single gather + row dot per request
                table = jnp.asarray(m.global_coefficients())
                note_shard(m.feature_shard, table.shape[-1], name)
                self._re_meta.append((name, m.feature_shard,
                                      m.random_effect_type))
                self._lookups[name] = _id_lookup(m.entity_ids)
                self._table_slot[name] = len(tables)
                tables.append(table)
            elif isinstance(m, MatrixFactorizationModel):
                self._mf_meta.append((name, m.row_effect_type,
                                      m.col_effect_type))
                self._lookups[name + "/row"] = _id_lookup(m.row_ids)
                self._lookups[name + "/col"] = _id_lookup(m.col_ids)
                tables.append(jnp.asarray(m.row_factors))
                tables.append(jnp.asarray(m.col_factors))
            else:
                raise TypeError(f"unknown coordinate model type {type(m)}")
        if not tables:
            raise ValueError("model has no coordinates to serve")
        # deliberately lock-free: delta publishers replace the WHOLE tuple
        # (never mutate in place) and scoring threads read it once per
        # batch — atomic publish at batch granularity
        self._tables = tuple(tables)  # photonlint: guarded-by=atomic
        self.feature_shards: Dict[str, int] = shard_dims
        self.entity_types = sorted(
            {t for _, _, t in self._re_meta}
            | {t for _, r, c in self._mf_meta for t in (r, c)})
        self._dtype = (jnp.result_type(*self._tables) if self._tables
                       else jnp.float32)
        # one jitted program, cached per bucket shape; tables are traced
        # ARGUMENTS (not closed-over constants), so a same-shape hot swap
        # reuses every compiled bucket program
        self._program = jax.jit(self._compute)
        self._seen_buckets: set = set()
        self.bucket_compiles = 0
        self.warmup_s = 0.0
        self.warmed = False
        # online-update version vector: seq of the newest applied delta
        # (0 = pristine full-model load) + lifetime apply/revert counts
        self.delta_seq = 0
        self.deltas_applied = 0
        self.deltas_reverted = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def from_model_dir(cls, model_dir: str, *, max_batch: int = 1024,
                       min_bucket: int = 8, version: Optional[str] = None,
                       warmup: bool = True) -> "CompiledScorer":
        from photon_ml_tpu.models.io import load_game_model
        model, _config = load_game_model(model_dir)
        scorer = cls(model, max_batch=max_batch, min_bucket=min_bucket,
                     version=version)
        if warmup:
            scorer.warmup()
        return scorer

    def bucket_sizes(self) -> List[int]:
        out, b = [], self.min_bucket
        while b < self.max_batch:
            out.append(b)
            b <<= 1
        out.append(self.max_batch)
        return out

    def warmup(self) -> float:
        """Compile every bucket program now, so no request ever does."""
        t0 = clock()
        with telemetry.span("serve_warmup", version=self.version):
            for b in self.bucket_sizes():
                xs = {s: np.zeros((b, d), np.float64)
                      for s, d in self.feature_shards.items()}
                lanes = {k: np.full(b, -1, np.int32) for k in self._lookups}
                jax.block_until_ready(self._run_bucket(xs, lanes, b))
        self.warmup_s = clock() - t0
        self.warmed = True
        return self.warmup_s

    # -- the device program ------------------------------------------------

    def _compute(self, tables, xs, lanes):
        """Summed coordinate margins for one padded bucket — ONE fused
        program (FE matvecs + RE gather-dots + MF factor dots), mirroring
        GameModel.score_dataset coordinate by coordinate."""
        i = 0
        total = None

        def add(z):
            nonlocal total
            total = z if total is None else total + z

        for _name, shard in self._fe_meta:
            w = tables[i]; i += 1
            add(xs[shard] @ w)
        for name, shard, _re_type in self._re_meta:
            table = tables[i]; i += 1
            add(score_by_entity(table, xs[shard], lanes[name]))
        for name, _row_t, _col_t in self._mf_meta:
            rf, cf = tables[i], tables[i + 1]; i += 2
            rl, cl = lanes[name + "/row"], lanes[name + "/col"]
            ok = (rl >= 0) & (cl >= 0)
            rfa = rf[jnp.maximum(rl, 0)]
            cfa = cf[jnp.maximum(cl, 0)]
            add(jnp.where(ok, jnp.sum(rfa * cfa, axis=-1), 0.0))
        return total

    def _run_bucket(self, xs, lanes, bucket: int):
        if bucket not in self._seen_buckets:
            self._seen_buckets.add(bucket)
            self.bucket_compiles += 1
        xs = {s: jnp.asarray(x, self._dtype) for s, x in xs.items()}
        lanes = {k: jnp.asarray(v) for k, v in lanes.items()}
        return self._program(self._tables, xs, lanes)

    # -- online row-level updates ------------------------------------------

    def updatable_coordinates(self) -> List[Tuple[str, str, str]]:
        """(name, feature_shard, re_type) of every coordinate whose stacked
        table accepts row-level delta swaps (plain + factored random
        effects; MF factor pairs are not online-updatable — prefer a full
        refit there)."""
        return list(self._re_meta)

    def re_table(self, name: str) -> jax.Array:
        """The device-resident stacked [E, d] table of one RE coordinate
        (original shard space — what apply_delta scatters into)."""
        return self._tables[self._table_slot[name]]

    def entity_row(self, name: str, entity_id) -> int:
        """Table row of a raw entity id under coordinate `name`
        (-1 = unseen at training time; such entities cannot be
        online-updated — the table has no row to anchor at)."""
        return self._lookups[name].get(entity_id, -1)

    def gather_rows(self, name: str, rows: np.ndarray) -> jax.Array:
        """Device gather of table rows (delta priors / anchors)."""
        return _gather_rows(self.re_table(name),
                            jnp.asarray(np.asarray(rows, np.int64)))

    def _scatter_coordinate(self, name: str, rows: np.ndarray,
                            values: np.ndarray) -> None:
        slot = self._table_slot.get(name)
        if slot is None:
            known = sorted(self._table_slot)
            raise KeyError(f"coordinate {name!r} has no online-updatable "
                           f"table (updatable: {known})")
        table = self._tables[slot]
        rows = np.asarray(rows, np.int64)
        values = np.asarray(values)
        if values.shape != (len(rows), table.shape[1]):
            raise ValueError(
                f"delta values for {name!r} must be [{len(rows)}, "
                f"{table.shape[1]}], got {values.shape}")
        if len(rows) and int(rows.max()) >= table.shape[0]:
            raise ValueError(
                f"delta row {int(rows.max())} out of range for {name!r} "
                f"(table has {table.shape[0]} rows)")
        rows_p, values_p = _pad_pow2_rows(rows, values, table.shape[0])
        new_table = _scatter_rows(table, jnp.asarray(rows_p),
                                  jnp.asarray(values_p, table.dtype))
        tables = list(self._tables)
        tables[slot] = new_table
        # one atomic tuple swap: a concurrent score() batch reads either
        # the old or the new tuple — batch-granularity consistency, same
        # contract as a full-model hot swap
        self._tables = tuple(tables)

    def scatter_rows(self, name: str, rows: np.ndarray,
                     values: np.ndarray) -> None:
        """Scatter raw row values into one coordinate's live table (the
        replication layer's replay primitive: rollback records and
        snapshot bootstraps carry explicit row states rather than
        ModelDeltas).  Callers serialize through the registry lock, same
        contract as apply_delta."""
        self._scatter_coordinate(name, rows, values)

    def warmup_delta(self, max_rows: int = 64) -> float:
        """Pre-compile the delta scatter programs for every pow-2 row
        count up to `max_rows` on every updatable table — the replica
        twin of OnlineUpdater.warmup's scatter block, so steady-state
        delta REPLAY traces nothing (a follower replica has no updater
        to warm these for it)."""
        t0 = clock()
        with telemetry.span("replica_delta_warmup", version=self.version):
            for name, _shard, _re_type in self.updatable_coordinates():
                table = self.re_table(name)
                k = 1
                bound = int(ceil_pow2(max(max_rows, 1)))
                while k <= bound:
                    rows = np.arange(min(k, table.shape[0]), dtype=np.int64)
                    vals = np.zeros((len(rows), table.shape[1]))
                    rows_p, vals_p = _pad_pow2_rows(rows, vals,
                                                    table.shape[0])
                    # result discarded: the live table is never touched
                    jax.block_until_ready(_scatter_rows(
                        table, jnp.asarray(rows_p),
                        jnp.asarray(vals_p, table.dtype)))
                    k <<= 1
        return clock() - t0

    def table_hashes(self):  # photonlint: flush-point -- audit endpoint: one deliberate full-table readback per call, never on the scoring path
        """sha256 of every device table's exact byte content, keyed by
        coordinate lane (MF factor pairs hash as name/row + name/col).
        The fleet audit primitive: two replicas whose version vectors AND
        table hashes agree converged bit-identically."""
        import hashlib
        i = 0
        out: Dict[str, str] = {}
        for name, _shard in self._fe_meta:
            out[name] = hashlib.sha256(
                np.ascontiguousarray(np.asarray(self._tables[i]))
                .tobytes()).hexdigest()
            i += 1
        for name, _shard, _re_type in self._re_meta:
            out[name] = hashlib.sha256(
                np.ascontiguousarray(np.asarray(self._tables[i]))
                .tobytes()).hexdigest()
            i += 1
        for name, _row_t, _col_t in self._mf_meta:
            for side in ("/row", "/col"):
                out[name + side] = hashlib.sha256(
                    np.ascontiguousarray(np.asarray(self._tables[i]))
                    .tobytes()).hexdigest()
                i += 1
        return out

    def apply_delta(self, delta) -> None:
        """Scatter a ModelDelta's changed rows into the live tables.
        Callers serialize through the registry lock; scoring threads need
        no lock (the table tuple swap is atomic, and the compiled bucket
        programs take tables as traced ARGUMENTS, so no re-trace)."""
        for name, cd in delta.coordinates.items():
            self._scatter_coordinate(name, cd.rows, cd.values)
        self.delta_seq = delta.seq
        self.deltas_applied += 1

    def revert_delta(self, delta) -> None:
        """Scatter a delta's pre-delta rows back (exact rollback: restores
        the bit pattern the rows had before apply_delta)."""
        for name, cd in delta.coordinates.items():
            self._scatter_coordinate(name, cd.rows, cd.prior)
        self.delta_seq = delta.seq - 1
        self.deltas_reverted += 1

    # -- request scoring ---------------------------------------------------

    def validate_request(self, features: Dict[str, np.ndarray],
                         ids: Dict[str, np.ndarray]) -> int:
        """Shape/coverage check -> the request's row count.  Raised errors
        are per-request (the batcher propagates them to one caller, not the
        whole batch)."""
        missing = sorted(set(self.feature_shards) - set(features))
        if missing:
            raise ValueError(f"request is missing feature shard(s) {missing}"
                             f" (model scores {sorted(self.feature_shards)})")
        n = None
        for shard, want in self.feature_shards.items():
            x = np.asarray(features[shard])
            if x.ndim != 2 or x.shape[1] != want:
                raise ValueError(
                    f"feature shard {shard!r} must be [n, {want}], got "
                    f"shape {x.shape}")
            if n is None:
                n = x.shape[0]
            elif x.shape[0] != n:
                raise ValueError(
                    f"feature shard {shard!r} has {x.shape[0]} rows; other "
                    f"shards have {n}")
        missing_ids = sorted(set(self.entity_types) - set(ids or {}))
        if missing_ids:
            raise ValueError(
                f"request is missing entity id column(s) {missing_ids} "
                f"(model has random effects over {self.entity_types})")
        for t in self.entity_types:
            col = np.asarray(ids[t])
            if n is None:
                n = len(col)
            if col.shape != (n,):
                raise ValueError(
                    f"id column {t!r} must be [{n}], got shape {col.shape}")
        if n is None or n == 0:
            raise ValueError("empty request")
        return n

    def _lanes_for_chunk(self, ids, lo, hi):
        lanes, hits, lookups = {}, 0, 0
        for name, _shard, re_type in self._re_meta:
            ln = _resolve_lanes(self._lookups[name],
                                np.asarray(ids[re_type])[lo:hi])
            lanes[name] = ln
            hits += int((ln >= 0).sum()); lookups += len(ln)
        for name, row_t, col_t in self._mf_meta:
            for side, t in (("/row", row_t), ("/col", col_t)):
                ln = _resolve_lanes(self._lookups[name + side],
                                    np.asarray(ids[t])[lo:hi])
                lanes[name + side] = ln
                hits += int((ln >= 0).sum()); lookups += len(ln)
        return lanes, hits, lookups

    def score(self, features: Dict[str, np.ndarray],
              ids: Optional[Dict[str, np.ndarray]] = None,
              ) -> ScoreBatchResult:
        """Margins for a request batch of any size (chunked at max_batch)."""
        ids = ids or {}
        n = self.validate_request(features, ids)
        out = np.empty(n, np.float64)
        buckets: List[int] = []
        hits = lookups = 0
        compiles0 = self.bucket_compiles
        for lo in range(0, n, self.max_batch):
            hi = min(lo + self.max_batch, n)
            m = hi - lo
            bucket = min(max(int(ceil_pow2(m)), self.min_bucket),
                         self.max_batch)
            pad = bucket - m
            xs = {}
            for shard in self.feature_shards:
                x = np.asarray(features[shard])[lo:hi]
                xs[shard] = (x if pad == 0 else
                             np.pad(x, ((0, pad), (0, 0))))
            lanes, h, lk = self._lanes_for_chunk(ids, lo, hi)
            if pad:
                lanes = {k: np.pad(v, (0, pad), constant_values=-1)
                         for k, v in lanes.items()}
            hits += h; lookups += lk
            buckets.append(bucket)
            z = self._run_bucket(xs, lanes, bucket)
            out[lo:hi] = np.asarray(z)[:m]
        return ScoreBatchResult(
            scores=out, num_rows=n, buckets=buckets,
            entity_lookups=lookups, entity_hits=hits,
            new_compiles=self.bucket_compiles - compiles0)

    def mean_prediction(self, scores: np.ndarray,
                        offsets: Optional[np.ndarray] = None) -> np.ndarray:
        """Inverse link over margins (+ offsets), like GameModel.predict."""
        if self._loss is None:
            raise ValueError(
                f"task {self.model.task_type!r} has no mean function")
        z = np.asarray(scores, np.float64)
        if offsets is not None:
            z = z + np.asarray(offsets, np.float64)
        return np.asarray(self._loss.mean(jnp.asarray(z)))

    def requests_from_dataset(self, dataset, rows: np.ndarray
                              ) -> Tuple[Dict[str, np.ndarray],
                                         Dict[str, np.ndarray]]:
        """Slice a GameDataset into (features, ids) request form — raw ids
        recovered through the dataset vocab; rows whose entity index is -1
        get a sentinel id no model contains (they stay fixed-effect-only).
        Sparse shards densify per request slice (serving requests are
        small dense rows by construction)."""
        def slice_rows(x):
            if hasattr(x, "tocsr"):  # scipy sparse shard
                return np.asarray(x.tocsr()[rows].todense())
            return np.asarray(x)[rows]

        feats = {s: slice_rows(dataset.feature_shards[s])
                 for s in self.feature_shards}
        ids = {}
        for t in self.entity_types:
            idx = np.asarray(dataset.entity_indices[t])[rows]
            vocab = np.asarray(dataset.entity_vocabs[t], dtype=object)
            raw = vocab[np.maximum(idx, 0)].copy()
            raw[idx < 0] = "\0__unseen__"
            ids[t] = raw
        return feats, ids
