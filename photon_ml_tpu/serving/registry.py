"""Versioned scorer registry: zero-downtime hot swap + rollback.

`load(version_dir)` does ALL the heavy work — model load, device transfer,
bucket warm-up compiles — on the calling (or a background) thread while the
previous scorer keeps serving; only the final reference swap happens under
the lock.  In-flight batches hold their own reference to the old scorer
(the batcher resolves the current scorer per batch), so a swap is atomic
at batch granularity and nothing is dropped.  The previous version is kept
for `rollback()`.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional, Tuple

from photon_ml_tpu.serving.scorer import CompiledScorer
from photon_ml_tpu.utils.events import EventEmitter, ModelSwapEvent


class ModelRegistry:
    def __init__(self, scorer_factory: Optional[Callable] = None,
                 emitter: Optional[EventEmitter] = None,
                 metrics=None):
        """`scorer_factory(version_dir, version)` -> warmed CompiledScorer;
        defaults to `CompiledScorer.from_model_dir`."""
        self._factory = scorer_factory or (
            lambda d, v: CompiledScorer.from_model_dir(d, version=v))
        self._emitter = emitter
        self._metrics = metrics
        self._lock = threading.Lock()
        self._counter = 0
        self._current: Optional[Tuple[str, CompiledScorer]] = None
        self._previous: Optional[Tuple[str, CompiledScorer]] = None

    @property
    def scorer(self) -> CompiledScorer:
        with self._lock:
            if self._current is None:
                raise RuntimeError("no model loaded")
            return self._current[1]

    @property
    def version(self) -> Optional[str]:
        with self._lock:
            return None if self._current is None else self._current[0]

    @property
    def previous_version(self) -> Optional[str]:
        with self._lock:
            return None if self._previous is None else self._previous[0]

    def _emit(self, event) -> None:
        if self._emitter is not None:
            self._emitter.send_event(event)

    def load(self, version_dir: str, version: Optional[str] = None) -> str:
        """Build + warm the new scorer, then swap atomically.  Blocks until
        the new model is live; use `load_async` to keep serving the old
        model from the calling thread too."""
        with self._lock:
            self._counter += 1
            if version is None:
                import os
                base = os.path.basename(str(version_dir).rstrip("/"))
                version = f"{base or 'model'}@{self._counter}"
        scorer = self._factory(version_dir, version)  # heavy, outside lock
        return self.install(scorer, version)

    def install(self, scorer: CompiledScorer, version: str) -> str:
        """Atomically make an already-built scorer the live one (the tail
        of `load`; also the path for swapping in an in-memory model)."""
        if not getattr(scorer, "warmed", True):
            scorer.warmup()
        with self._lock:
            previous = self._current
            self._previous = previous
            self._current = (version, scorer)
        if self._metrics is not None:
            self._metrics.observe_swap()
        self._emit(ModelSwapEvent(
            time=time.time(), version=version,
            previous_version=None if previous is None else previous[0],
            action="swap", warmup_s=getattr(scorer, "warmup_s", 0.0)))
        return version

    def load_async(self, version_dir: str,
                   version: Optional[str] = None) -> "Future[str]":
        """Background hot swap: returns a Future resolving to the new
        version id once it is live."""
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.load(version_dir, version))
            except BaseException as e:  # surface through the future
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="photon-serving-swap").start()
        return fut

    def rollback(self) -> str:
        """Swap back to the previous version (single-level undo)."""
        with self._lock:
            if self._previous is None:
                raise RuntimeError("no previous model version to roll back to")
            rolled_from = self._current
            self._current, self._previous = self._previous, rolled_from
            version = self._current[0]
        if self._metrics is not None:
            self._metrics.observe_swap(rollback=True)
        self._emit(ModelSwapEvent(
            time=time.time(), version=version,
            previous_version=None if rolled_from is None else rolled_from[0],
            action="rollback"))
        return version
