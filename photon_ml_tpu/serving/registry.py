"""Versioned scorer registry: zero-downtime hot swap, row-level delta
swaps, and delta-aware rollback.

`load(version_dir)` does ALL the heavy work — model load, device transfer,
bucket warm-up compiles — on the calling (or a background) thread while the
previous scorer keeps serving; only the final reference swap happens under
the lock.  In-flight batches hold their own reference to the old scorer
(the batcher resolves the current scorer per batch), so a swap is atomic
at batch granularity and nothing is dropped.  The previous version is kept
for `rollback()`.

Row-level deltas (the online tier, photon_ml_tpu/online/): `apply_delta`
scatters a ModelDelta's changed random-effect rows into the LIVE scorer's
device tables under the registry lock — no full-model cutover, no fresh
XLA traces.  The delta's version vector must match the live version
(`StaleDeltaError` otherwise: rows solved against a superseded model must
never land on its successor), and every applied delta is kept on an undo
log so `rollback()` is DELTA-AWARE: with pending deltas it restores the
exact pre-delta rows (newest first — bit-exact round trip); with none it
falls back to the full-model previous-version swap.  A full-model rollback
restores the previous scorer AS LAST SERVED, i.e. including any deltas it
had absorbed before being swapped out.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional, Tuple

import numpy as np

from photon_ml_tpu.serving.scorer import CompiledScorer
from photon_ml_tpu.telemetry import flight
from photon_ml_tpu.utils import faults, locktrace
from photon_ml_tpu.utils.events import (EventEmitter, ModelDeltaEvent,
                                        ModelSwapEvent)

logger = logging.getLogger("photon_ml_tpu")


class StaleDeltaError(RuntimeError):
    """A delta's base_version no longer matches the live scorer (a full
    swap landed between solve and publish).  The publisher should re-solve
    against the new version — applying anyway would scatter rows computed
    against stale residual margins."""


#: default undo-log depth: deltas are a few KB each, so this bounds
#: memory at a few MB while keeping hours of update history rollback-able.
#: The bound is configurable (`ModelRegistry(max_delta_log=...)`,
#: ServingConfig.max_delta_log, cli.serve --max-delta-log).  When the log
#: overflows, the OLDEST records drop — LOUDLY (warning log + the
#: serve.rollback_degraded counter when a rollback then has to fall back)
#: — and `rollback()` DEGRADES to a full-model rollback, because partial
#: delta restoration would not be the exact pre-delta state.
MAX_DELTA_LOG = 4096


class ModelRegistry:
    def __init__(self, scorer_factory: Optional[Callable] = None,
                 emitter: Optional[EventEmitter] = None,
                 metrics=None, max_delta_log: int = MAX_DELTA_LOG):
        """`scorer_factory(version_dir, version)` -> warmed CompiledScorer;
        defaults to `CompiledScorer.from_model_dir`."""
        self._factory = scorer_factory or (
            lambda d, v: CompiledScorer.from_model_dir(d, version=v))
        self._emitter = emitter
        self._metrics = metrics
        self._lock = locktrace.tracked(threading.Lock(),
                                       "ModelRegistry._lock")
        self._counter = 0
        self._current: Optional[Tuple[str, CompiledScorer]] = None
        self._previous: Optional[Tuple[str, CompiledScorer]] = None
        self._max_delta_log = int(max_delta_log)
        self._delta_log: deque = deque()
        self._delta_log_truncated = False
        self._delta_seq = 0
        self._swap_hooks: list = []
        # ordered model-state change feed (the replication log's source):
        # every mutation reserves a ticket UNDER the lock, hooks run
        # OUTSIDE it with (ticket, event) so a publisher can restore the
        # mutation order even when hook invocations race
        self._publish_hooks: list = []
        self._publish_ticket = 0                          # photonlint: guarded-by=_lock

    def add_publish_hook(self, fn: Callable[[int, dict], None]) -> int:
        """`fn(ticket, event)` runs after EVERY model-state change —
        full-model install, row-level delta, delta-aware rollback,
        full-model rollback — outside the registry lock.  Tickets are
        assigned under the lock at mutation time, so sorting events by
        ticket reconstructs the exact mutation order even when two hook
        invocations race on different threads (fleet.FleetPublisher
        relies on this to keep the replication log ordered).  Returns the
        next ticket that will be assigned, so a publisher attaching to a
        live registry knows where its event stream starts."""
        with self._lock:
            self._publish_hooks.append(fn)
            return self._publish_ticket

    def _run_publish_hooks(self, ticket: int, event: dict) -> None:
        for fn in list(self._publish_hooks):
            try:
                fn(ticket, event)
            except Exception:  # a broken publisher must not block serving
                logger.exception("publish hook %r failed for ticket %d %r",
                                 fn, ticket, event.get("kind"))

    def add_swap_hook(self, fn: Callable[[str, str], None]) -> None:
        """`fn(version, action)` runs after every FULL-model change —
        install ("swap") and full-model rollback ("rollback"), never a
        row-level delta — outside the registry lock.  The health monitor
        registers here to snapshot its drift baseline per install."""
        self._swap_hooks.append(fn)

    def _run_swap_hooks(self, version: str, action: str) -> None:
        for fn in list(self._swap_hooks):
            try:
                fn(version, action)
            except Exception:  # a broken observer must not block a swap
                logger.exception("swap hook %r failed for %s %r",
                                 fn, action, version)

    @property
    def scorer(self) -> CompiledScorer:
        with self._lock:
            if self._current is None:
                raise RuntimeError("no model loaded")
            return self._current[1]

    @property
    def version(self) -> Optional[str]:
        with self._lock:
            return None if self._current is None else self._current[0]

    @property
    def previous_version(self) -> Optional[str]:
        with self._lock:
            return None if self._previous is None else self._previous[0]

    def _emit(self, event) -> None:
        if self._emitter is not None:
            self._emitter.send_event(event)

    def load(self, version_dir: str, version: Optional[str] = None) -> str:
        """Build + warm the new scorer, then swap atomically.  Blocks until
        the new model is live; use `load_async` to keep serving the old
        model from the calling thread too."""
        with self._lock:
            self._counter += 1
            if version is None:
                import os
                base = os.path.basename(str(version_dir).rstrip("/"))
                version = f"{base or 'model'}@{self._counter}"
        scorer = self._factory(version_dir, version)  # heavy, outside lock
        return self.install(scorer, version, source_dir=version_dir)

    def install(self, scorer: CompiledScorer, version: str,
                source_dir: Optional[str] = None) -> str:
        """Atomically make an already-built scorer the live one (the tail
        of `load`; also the path for swapping in an in-memory model).
        `source_dir` is the model directory the scorer was built from
        (None for in-memory models) — the replication publisher records
        it so replicas can replay the swap."""
        if not getattr(scorer, "warmed", True):
            scorer.warmup()
        # the scorer must carry the version it is installed under: delta
        # publishers stamp `scorer.version` into their version vector, and
        # a None/mismatched version would refuse every delta as stale
        scorer.version = version
        with self._lock:
            previous = self._current
            self._previous = previous
            self._current = (version, scorer)
            # the undo log belongs to the outgoing version: a new full
            # model starts pristine (the previous scorer keeps its
            # absorbed deltas in its tables — that is the state it last
            # served, and what a full-model rollback restores)
            self._delta_log.clear()
            self._delta_log_truncated = False
            self._delta_seq = 0
            ticket = self._publish_ticket
            self._publish_ticket += 1
        if self._metrics is not None:
            self._metrics.observe_swap()
        self._emit(ModelSwapEvent(
            time=time.time(), version=version,
            previous_version=None if previous is None else previous[0],
            action="swap", warmup_s=getattr(scorer, "warmup_s", 0.0)))
        self._run_publish_hooks(ticket, {
            "kind": "swap", "version": version,
            "previous_version": None if previous is None else previous[0],
            "source_dir": None if source_dir is None else str(source_dir)})
        self._run_swap_hooks(version, "swap")
        return version

    def load_async(self, version_dir: str,
                   version: Optional[str] = None) -> "Future[str]":
        """Background hot swap: returns a Future resolving to the new
        version id once it is live."""
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.load(version_dir, version))
            except BaseException as e:  # surface through the future
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="photon-serving-swap").start()
        return fut

    # -- row-level delta swaps (the online tier's publish path) -------------

    def next_delta_seq(self) -> int:
        """Reserve the next delta sequence number for the live version
        (the publisher stamps it into the ModelDelta it is building)."""
        with self._lock:
            return self._delta_seq + 1

    def apply_delta(self, delta, publish_s: float = 0.0) -> dict:
        """Scatter a ModelDelta's rows into the LIVE scorer under the
        lock.  Verifies the version vector (StaleDeltaError on mismatch)
        and appends the delta to the undo log.  Returns the resulting
        version vector."""
        faults.fire("online.publish",
                    coordinate=",".join(sorted(delta.coordinates)))
        with self._lock:
            if self._current is None:
                raise RuntimeError("no model loaded")
            version, scorer = self._current
            if delta.base_version != version:
                raise StaleDeltaError(
                    f"delta was solved against version "
                    f"{delta.base_version!r} but {version!r} is live — "
                    "re-solve against the current model")
            scorer.apply_delta(delta)
            self._delta_seq = delta.seq
            self._delta_log.append(delta)
            overflowed = len(self._delta_log) > self._max_delta_log
            if overflowed:
                self._delta_log.popleft()
                first_overflow = not self._delta_log_truncated
                self._delta_log_truncated = True
            pending = len(self._delta_log)
            ticket = self._publish_ticket
            self._publish_ticket += 1
        if overflowed and first_overflow:
            # LOUD, once per overflow episode: from here on an exact
            # delta-aware rollback is impossible and rollback() will
            # degrade to a full-model swap (serve.rollback_degraded)
            logger.error(
                "delta undo log overflowed its bound of %d: oldest "
                "records dropped — delta-aware rollback DEGRADES to a "
                "full-model rollback until the next install (raise "
                "max_delta_log / --max-delta-log if exact rollback "
                "across this much update history is required)",
                self._max_delta_log)
        if self._metrics is not None:
            self._metrics.observe_delta(rows=delta.num_rows,
                                        publish_s=publish_s)
        self._emit(ModelDeltaEvent(
            time=time.time(), version=version, delta_seq=delta.seq,
            coordinates={n: cd.num_rows
                         for n, cd in delta.coordinates.items()},
            num_rows=delta.num_rows, publish_s=publish_s))
        self._run_publish_hooks(ticket, {"kind": "delta", "delta": delta,
                                         "version": version})
        return {"version": version, "delta_seq": delta.seq,
                "pending_deltas": pending}

    def pending_deltas(self) -> int:
        """Deltas applied to the live version and still rollback-able."""
        with self._lock:
            return len(self._delta_log)

    def applied_deltas(self) -> tuple:
        """Snapshot of the live version's undo log, oldest first (audit /
        replication: models.io.save_model_delta persists these)."""
        with self._lock:
            return tuple(self._delta_log)

    def version_vector(self) -> dict:
        with self._lock:
            version = None if self._current is None else self._current[0]
            seq = self._delta_seq
        return {"version": version, "delta_seq": seq}

    def rollback(self) -> str:
        """Delta-aware single-level undo.

        With pending deltas: restore the exact pre-delta rows (reverting
        newest-first, so rows touched by several deltas land back on their
        original values bit-exactly) and stay on the current full-model
        version.  With none: swap back to the previous full model.  With a
        TRUNCATED undo log (overflow dropped the oldest records): an exact
        pre-delta restore is impossible, so the rollback DEGRADES to the
        full-model path — loudly (error log + serve.rollback_degraded on
        both metric surfaces)."""
        degraded = False
        with self._lock:
            if self._delta_log and self._delta_log_truncated:
                if self._previous is None:
                    raise RuntimeError(
                        "delta undo log overflowed (oldest records "
                        "dropped) and no previous full model exists: "
                        "neither an exact pre-delta restore nor a "
                        "full-model rollback is possible — swap in a "
                        "known-good model version instead")
                degraded = True
                self._delta_log.clear()
                self._delta_log_truncated = False
            if self._delta_log:
                version, scorer = self._current
                # fold the restored row state (oldest delta's prior wins
                # per row: that is the value the newest-first revert loop
                # below lands on) for the replication publish hook
                restored: dict = {}
                for delta in self._delta_log:          # oldest first
                    for lane, cd in delta.coordinates.items():
                        lane_rows = restored.setdefault(lane, {})
                        for r, p in zip(cd.rows.tolist(), cd.prior):
                            if r not in lane_rows:
                                lane_rows[r] = p
                reverted = 0
                while self._delta_log:
                    scorer.revert_delta(self._delta_log.pop())
                    reverted += 1
                self._delta_seq = 0
                rolled_from = None
            else:
                if self._previous is None:
                    raise RuntimeError(
                        "no previous model version to roll back to")
                rolled_from = self._current
                self._current, self._previous = self._previous, rolled_from
                version = self._current[0]
                reverted = 0
                restored = {}
                self._delta_seq = self._current[1].delta_seq
            ticket = self._publish_ticket
            self._publish_ticket += 1
        if degraded:
            if self._metrics is not None:
                self._metrics.observe_rollback_degraded()
            logger.error(
                "rollback DEGRADED to a full-model swap (-> %r): the "
                "delta undo log had overflowed, so the exact pre-delta "
                "rows are gone — the restored state is the previous "
                "version AS LAST SERVED, not the pre-delta tables",
                version)
        if self._metrics is not None:
            self._metrics.observe_swap(rollback=True)
        self._emit(ModelSwapEvent(
            time=time.time(), version=version,
            previous_version=(None if rolled_from is None
                              else rolled_from[0]),
            action="delta_rollback" if reverted else "rollback"))
        if reverted:
            self._run_publish_hooks(ticket, {
                "kind": "delta_rollback", "version": version,
                "to_delta_seq": 0,
                "restored": {lane: (np.asarray(sorted(rows), np.int64),
                                    np.stack([rows[r]
                                              for r in sorted(rows)]))
                             for lane, rows in restored.items()}})
        else:
            self._run_publish_hooks(ticket, {
                "kind": "rollback", "version": version,
                "previous_version": (None if rolled_from is None
                                     else rolled_from[0]),
                "degraded": degraded})
            # delta rollback keeps the same full-model version live: the
            # health baseline is carried, exactly like a delta publish
            self._run_swap_hooks(version, "rollback")
        # a rollback IS the postmortem moment: flush the flight ring so
        # the window that led here (gate trips, stale deltas, the
        # operator action) is on disk in every process that executes one
        # — publishers directly, replicas when they replay the record
        flight.trigger("model.rollback", version=str(version),
                       kind="delta_rollback" if reverted else "rollback",
                       degraded=degraded)
        return version

    def replay_row_state(self, restored: dict, version: str,
                         to_delta_seq: int) -> None:
        """Replication replay primitive: scatter explicit row states into
        the LIVE scorer and pin the delta seq — how a replica applies a
        delta_rollback record (the restored rows ride in the record, so
        even a snapshot-bootstrapped replica with no local undo history
        converges bit-identically) and how a snapshot bootstrap lands its
        folded rows.  `restored` maps lane -> (rows [k], values [k, d])."""
        with self._lock:
            if self._current is None:
                raise RuntimeError("no model loaded")
            if self._current[0] != version:
                raise StaleDeltaError(
                    f"row-state replay targets version {version!r} but "
                    f"{self._current[0]!r} is live — the replicated "
                    "record stream is out of order")
            scorer = self._current[1]
            for lane, (rows, values) in restored.items():
                scorer.scatter_rows(lane, rows, values)
            scorer.delta_seq = int(to_delta_seq)
            self._delta_seq = int(to_delta_seq)
            # the explicit row state replaces whatever per-delta undo
            # history this registry held for the current version
            self._delta_log.clear()
            self._delta_log_truncated = False
