from photon_ml_tpu.models.coefficients import Coefficients  # noqa: F401
from photon_ml_tpu.models.glm import (  # noqa: F401
    TASK_MODELS, GeneralizedLinearModel, LinearRegressionModel,
    LogisticRegressionModel, PoissonRegressionModel,
    SmoothedHingeLossLinearSVMModel, model_for_task,
)
from photon_ml_tpu.models.training import TrainedModel, best_model_by_validation, train_glm  # noqa: F401
from photon_ml_tpu.models.game import (  # noqa: F401
    FactoredRandomEffectModel, FixedEffectModel, GameModel,
    MatrixFactorizationModel, RandomEffectModel,
)
from photon_ml_tpu.models.validators import (  # noqa: F401
    BinaryClassifierAUCValidator, BinaryPredictionValidator,
    CompositeModelValidator, MaximumDifferenceValidator, ModelValidationError,
    NonNegativePredictionValidator, PredictionFiniteValidator,
)
