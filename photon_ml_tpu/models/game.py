"""GAME model containers: fixed-effect, random-effect, and the composite.

reference:
  - DatumScoringModel (photon-lib/.../model/DatumScoringModel.scala:32-52)
  - GameModel (photon-lib/.../model/GameModel.scala:32-168): coordinate map,
    total score = sum of sub-scores, consistent task check
  - FixedEffectModel (photon-api/.../model/FixedEffectModel.scala:31)
  - RandomEffectModel (photon-api/.../model/RandomEffectModel.scala:38-290)
  - RandomEffectModelInProjectedSpace (.../RandomEffectModelInProjectedSpace.scala)

Scoring semantics follow the reference: a model's score is ITS margin
contribution only (no base offset — evaluators add score+offset,
Evaluator.scala:35-45), and rows whose entity is unknown to a random-effect
model contribute 0 (the reference's missing-score default).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops import losses as L
from photon_ml_tpu.parallel.random_effect import score_by_entity


from photon_ml_tpu.parallel.mesh import pad_and_shard_rows as _sharded_rows


@dataclasses.dataclass
class FixedEffectModel:
    """One global GLM bound to a feature shard (reference:
    FixedEffectModel.scala — the Broadcast wrapper is obsolete: coefficients
    are just a device array, replicated by sharding when distributed)."""

    glm: GeneralizedLinearModel
    feature_shard: str

    @property
    def task_type(self) -> str:
        return type(self.glm).task_type

    def score_dataset(self, dataset: GameDataset, mesh=None) -> jax.Array:
        x = dataset.device_shard(self.feature_shard)
        if mesh is not None:
            from photon_ml_tpu.parallel.fixed_effect import score_fixed_effect
            # key the staged sharded design matrix per (dataset, shard):
            # repeated rescoring (every coordinate update touches the
            # validation set) re-transfers nothing
            return score_fixed_effect(
                self.glm, x, mesh,
                residency_key=("score", id(dataset), self.feature_shard))
        return self.glm.compute_score(x)

    def summary(self) -> str:
        c = self.glm.coefficients.means
        return (f"FixedEffectModel(shard={self.feature_shard}, dim={c.shape[-1]}, "
                f"|w|={float(jnp.linalg.norm(c)):.4g})")


def _lanes_for(dataset: GameDataset, re_type: str,
               entity_ids: np.ndarray) -> np.ndarray:
    """Map the dataset's entity-index column to model lanes by raw id — the
    static-gather replacement for the reference's data-keyBy(REId) ⋈ model
    join (RandomEffectModel.scala:256)."""
    vocab = dataset.entity_vocabs[re_type]
    lookup = {v: i for i, v in enumerate(entity_ids.tolist())}
    vocab_to_lane = np.asarray([lookup.get(v, -1) for v in vocab.tolist()],
                               dtype=np.int64)
    idx = dataset.entity_indices[re_type]
    return np.where(idx >= 0, vocab_to_lane[np.maximum(idx, 0)], -1)


def _device_lanes(dataset: GameDataset, re_type: str,
                  entity_ids: np.ndarray) -> jax.Array:
    """_lanes_for on device, memoized per (dataset, entity vocabulary): the
    lane map is identical across every update's rescoring (models are
    rebuilt per update but share the entity_ids array)."""
    key = ("lanes", re_type)
    hit = dataset._scoring_cache.get(key)
    if hit is not None and hit[0] is entity_ids:
        return hit[1]
    lanes = jnp.asarray(_lanes_for(dataset, re_type, entity_ids))
    dataset._scoring_cache[key] = (entity_ids, lanes)
    return lanes


@dataclasses.dataclass
class RandomEffectModel:
    """Per-entity coefficients in a (possibly projected) local space.

    Like the reference's RandomEffectModelInProjectedSpace, the model stores
    compact local-space coefficients plus the projection back to the global
    shard space; entity identity is carried as raw id strings so the model
    scores datasets with different vocabularies (reference keys the model
    RDD by REId for the same reason)."""

    random_effect_type: str
    feature_shard: str
    task_type: str
    coefficients: jax.Array               # [E, d_local]
    entity_ids: np.ndarray                # [E] raw entity id values
    projection: Optional[np.ndarray]      # [E, d_local] global cols, -1 pad
    global_dim: int
    variances: Optional[jax.Array] = None  # [E, d_local]
    # dense shared random-projection matrix [d_local, d_global] (reference:
    # ProjectionMatrixBroadcast) — exclusive with the index `projection`
    projection_matrix: Optional[jax.Array] = None

    def __post_init__(self):
        # device-resident once: scoring runs every coordinate-descent update
        if self.projection_matrix is not None:
            self.projection_matrix = jnp.asarray(self.projection_matrix)

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)

    def global_coefficients(self) -> jax.Array:
        """[E, d_global] via scatter (reference:
        IndexMapProjectorRDD.projectCoefficientsRDD) or dense P^T c
        (reference: ProjectionMatrixBroadcast.projectCoefficientsRDD)."""
        if self.projection_matrix is not None:
            return self.coefficients @ self.projection_matrix
        from photon_ml_tpu.parallel.random_effect import scatter_local_to_global
        return scatter_local_to_global(self.coefficients, self.projection,
                                       self.global_dim)

    def lanes_for(self, dataset: GameDataset) -> np.ndarray:
        return _lanes_for(dataset, self.random_effect_type, self.entity_ids)

    def _device_lanes(self, dataset: GameDataset) -> jax.Array:
        return _device_lanes(dataset, self.random_effect_type,
                             self.entity_ids)

    def score_dataset(self, dataset: GameDataset, mesh=None) -> jax.Array:
        from photon_ml_tpu.parallel.random_effect import (
            score_entities_matmul, score_entities_plain,
            score_entities_scatter)
        x = dataset.device_shard(self.feature_shard)
        lanes = self._device_lanes(dataset)
        if mesh is not None:
            n, (x, lanes) = _sharded_rows(
                mesh, x, lanes,
                residency_key=("score", id(dataset), self.feature_shard))
            return score_by_entity(self.global_coefficients(), x, lanes)[:n]
        # single fused program per shape (projection + gather + dot): over a
        # tunneled device each op-by-op program pays an executable upload
        if self.projection_matrix is not None:
            return score_entities_matmul(self.coefficients,
                                         self.projection_matrix, x, lanes)
        if self.projection is not None:
            key = ("proj", self.random_effect_type)
            hit = dataset._scoring_cache.get(key)
            if hit is None or hit[0] is not self.projection:
                hit = (self.projection, jnp.asarray(self.projection))
                dataset._scoring_cache[key] = hit
            return score_entities_scatter(self.coefficients, hit[1], x,
                                          lanes, global_dim=self.global_dim)
        return score_entities_plain(self.coefficients, x, lanes)

    def summary(self) -> str:
        return (f"RandomEffectModel(type={self.random_effect_type}, "
                f"shard={self.feature_shard}, entities={self.num_entities}, "
                f"local_dim={self.coefficients.shape[-1]})")


@dataclasses.dataclass
class FactoredRandomEffectModel:
    """Per-entity latent factors [E, k] + a shared latent projection [k, d].

    reference: FactoredRandomEffectModel (photon-api/.../model/
    FactoredRandomEffectModel.scala:33) = modelsInProjectedSpace +
    ProjectionMatrixBroadcast.  Effective per-entity coefficients in the
    original shard space are C @ P — computed lazily for scoring (a single
    [E,k]x[k,d] MXU matmul instead of the reference's per-entity
    projectCoefficients map)."""

    random_effect_type: str
    feature_shard: str
    task_type: str
    latent_coefficients: jax.Array        # [E, k]
    projection: jax.Array                 # [k, d_global]
    entity_ids: np.ndarray                # [E] raw entity id values
    global_dim: int

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)

    @property
    def latent_dim(self) -> int:
        return self.latent_coefficients.shape[1]

    def global_coefficients(self) -> jax.Array:
        return self.latent_coefficients @ self.projection

    def to_random_effect_model(self) -> RandomEffectModel:
        """Original-space view (reference: FactoredRandomEffectModel
        .toRandomEffectModel)."""
        return RandomEffectModel(
            random_effect_type=self.random_effect_type,
            feature_shard=self.feature_shard, task_type=self.task_type,
            coefficients=self.global_coefficients(), entity_ids=self.entity_ids,
            projection=None, global_dim=self.global_dim)

    def score_dataset(self, dataset: GameDataset, mesh=None) -> jax.Array:
        if mesh is None:
            from photon_ml_tpu.parallel.random_effect import \
                score_entities_matmul
            return score_entities_matmul(
                self.latent_coefficients, self.projection,
                dataset.device_shard(self.feature_shard),
                _device_lanes(dataset, self.random_effect_type,
                              self.entity_ids))
        return self.to_random_effect_model().score_dataset(dataset, mesh)

    def summary(self) -> str:
        return (f"FactoredRandomEffectModel(type={self.random_effect_type}, "
                f"shard={self.feature_shard}, entities={self.num_entities}, "
                f"latent_dim={self.latent_dim})")


@dataclasses.dataclass
class MatrixFactorizationModel:
    """score(row, col) = rowFactor . colFactor.

    reference: MatrixFactorizationModel (photon-api/.../model/
    MatrixFactorizationModel.scala:36-291) — RDDs of (id, Vector) latent
    factors; here two dense [*, k] arrays + host-side id arrays.  Like the
    reference (modelType = TaskType.NONE), this model is task-agnostic:
    task_type "none" is exempt from GameModel's consistency check."""

    row_effect_type: str
    col_effect_type: str
    row_factors: jax.Array                # [R, k]
    row_ids: np.ndarray                   # [R] raw entity id values
    col_factors: jax.Array                # [C, k]
    col_ids: np.ndarray                   # [C] raw entity id values
    task_type: str = "none"

    @property
    def num_latent_factors(self) -> int:
        """reference: MatrixFactorizationModel.numLatentFactors."""
        if self.row_factors.shape[0]:
            return self.row_factors.shape[1]
        if self.col_factors.shape[0]:
            return self.col_factors.shape[1]
        return 0

    @staticmethod
    def _lanes(dataset: GameDataset, effect_type: str, ids: np.ndarray) -> np.ndarray:
        vocab = dataset.entity_vocabs[effect_type]
        lookup = {v: i for i, v in enumerate(ids.tolist())}
        vocab_to_lane = np.asarray([lookup.get(v, -1) for v in vocab.tolist()],
                                   dtype=np.int64)
        idx = dataset.entity_indices[effect_type]
        return np.where(idx >= 0, vocab_to_lane[np.maximum(idx, 0)], -1)

    def score_dataset(self, dataset: GameDataset, mesh=None) -> jax.Array:
        """rowFactor.colFactor per row; either side unseen -> 0 (reference:
        MatrixFactorizationModel.score inner join — missing pairs default)."""
        rl = jnp.asarray(self._lanes(dataset, self.row_effect_type, self.row_ids))
        cl = jnp.asarray(self._lanes(dataset, self.col_effect_type, self.col_ids))
        n = rl.shape[0]
        if mesh is not None:
            # pad with -1 (unseen) so padding rows score 0
            n, (rl, cl) = _sharded_rows(mesh, rl + 1, cl + 1)
            rl, cl = rl - 1, cl - 1
        ok = (rl >= 0) & (cl >= 0)
        rf = self.row_factors[jnp.maximum(rl, 0)]
        cf = self.col_factors[jnp.maximum(cl, 0)]
        return jnp.where(ok, jnp.sum(rf * cf, axis=-1), 0.0)[:n]

    @staticmethod
    def from_factored(model: FactoredRandomEffectModel,
                      col_effect_type: str,
                      col_ids: np.ndarray) -> "MatrixFactorizationModel":
        """When the factored RE's feature shard is a one-hot indicator of a
        second entity (no intercept), c_e . (P x) == c_e . P[:, col]: rows
        are the RE entities, columns are the projection's columns."""
        if len(col_ids) != model.projection.shape[1]:
            raise ValueError(
                f"col_ids has {len(col_ids)} entries but the projection has "
                f"{model.projection.shape[1]} columns — the feature shard "
                "must be a one-hot column indicator")
        return MatrixFactorizationModel(
            row_effect_type=model.random_effect_type,
            col_effect_type=col_effect_type,
            row_factors=model.latent_coefficients,
            row_ids=model.entity_ids,
            col_factors=model.projection.T,
            col_ids=np.asarray(col_ids))

    def summary(self) -> str:
        return (f"MatrixFactorizationModel(rows={self.row_effect_type}x"
                f"{len(self.row_ids)}, cols={self.col_effect_type}x"
                f"{len(self.col_ids)}, k={self.num_latent_factors})")


CoordinateModel = (FixedEffectModel | RandomEffectModel
                   | FactoredRandomEffectModel | MatrixFactorizationModel)


@dataclasses.dataclass
class GameModel:
    """Ordered coordinate -> model map; total score is the sum.

    reference: GameModel.scala:32-168 incl. the consistent-task check
    (line 163)."""

    coordinates: Dict[str, CoordinateModel]
    task_type: str

    def __post_init__(self):
        for name, m in self.coordinates.items():
            # "none" = task-agnostic (matrix factorization; reference sets
            # modelType = TaskType.NONE for it, MatrixFactorizationModel.scala)
            if m.task_type not in (self.task_type, "none"):
                raise ValueError(
                    f"coordinate {name!r} has task {m.task_type!r}, "
                    f"expected {self.task_type!r} (reference: GameModel task "
                    "consistency check)")

    @property
    def loss(self) -> L.PointwiseLoss:
        return L.TASK_LOSSES[self.task_type]

    def score_dataset(self, dataset: GameDataset, mesh=None) -> jax.Array:
        """Sum of coordinate margins (reference: GameModel.scala:101-112).
        With a mesh, every coordinate scores row-sharded over the data axis
        (the reference's scoring driver is always distributed)."""
        total = jnp.zeros(dataset.num_rows)
        for m in self.coordinates.values():
            total = total + m.score_dataset(dataset, mesh)
        return total

    def predict(self, dataset: GameDataset, mesh=None) -> jax.Array:
        z = self.score_dataset(dataset, mesh)
        if dataset.offsets is not None:
            z = z + jnp.asarray(dataset.offsets)
        return self.loss.mean(z)

    def summary(self) -> str:
        lines = [f"GameModel(task={self.task_type})"]
        lines += [f"  {name}: {m.summary()}" for name, m in self.coordinates.items()]
        return "\n".join(lines)
