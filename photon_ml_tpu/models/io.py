"""Model persistence: the checkpoint format.

Rebuild of ModelProcessingUtils (photon-client/.../data/avro/
ModelProcessingUtils.scala:58-669): GAME models persist to a directory tree

    <dir>/model-metadata.json                     # task, config, coordinates
    <dir>/fixed-effect/<name>/coefficients.npz    # means (+variances)
    <dir>/random-effect/<name>/coefficients.npz   # [E, d_local] + projection
                                                  # + entity ids + global dim

mirroring the reference's fixed-effect/<coord>/coefficients/part-*.avro and
random-effect/<coord>/... layout with npz in place of Avro records (an Avro
export for cross-tool parity lives in photon_ml_tpu/data/avro_io.py).
model-metadata.json embeds the full training config JSON exactly like the
reference embeds optimizer configs for scoring-side reproducibility
(ModelProcessingUtils.scala:517-559).  Feature names are stored when an
IndexMap is provided, matching the reference's human-readable name.term
output.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.game.config import GameTrainingConfig
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FactoredRandomEffectModel, FixedEffectModel, GameModel,
    MatrixFactorizationModel, RandomEffectModel,
)
from photon_ml_tpu.models.glm import model_for_task

_FORMAT_VERSION = 1


def save_game_model(
    model: GameModel,
    directory: str,
    config: Optional[GameTrainingConfig] = None,
    index_maps: Optional[Dict[str, IndexMap]] = None,
) -> None:
    """reference: ModelProcessingUtils.saveGameModelsToHDFS (scala:71-135)."""
    os.makedirs(directory, exist_ok=True)
    meta = {"format_version": _FORMAT_VERSION, "task_type": model.task_type,
            "coordinates": {}, "config": config.to_dict() if config else None}
    for name, m in model.coordinates.items():
        if isinstance(m, FixedEffectModel):
            sub = os.path.join(directory, "fixed-effect", name)
            os.makedirs(sub, exist_ok=True)
            arrays = {"means": np.asarray(m.glm.coefficients.means)}
            if m.glm.coefficients.variances is not None:
                arrays["variances"] = np.asarray(m.glm.coefficients.variances)
            imap = (index_maps or {}).get(m.feature_shard)
            if imap is not None:
                arrays["feature_keys"] = imap.index_to_key.astype(object)
            np.savez_compressed(os.path.join(sub, "coefficients.npz"), **arrays)
            meta["coordinates"][name] = {"kind": "fixed_effect",
                                         "feature_shard": m.feature_shard}
        elif isinstance(m, RandomEffectModel):
            sub = os.path.join(directory, "random-effect", name)
            os.makedirs(sub, exist_ok=True)
            arrays = {"coefficients": np.asarray(m.coefficients),
                      "entity_ids": np.asarray(m.entity_ids).astype(object),
                      "global_dim": np.asarray(m.global_dim)}
            if m.projection is not None:
                arrays["projection"] = m.projection
            if m.projection_matrix is not None:
                arrays["projection_matrix"] = np.asarray(m.projection_matrix)
            if m.variances is not None:
                arrays["variances"] = np.asarray(m.variances)
            np.savez_compressed(os.path.join(sub, "coefficients.npz"), **arrays)
            meta["coordinates"][name] = {
                "kind": "random_effect",
                "random_effect_type": m.random_effect_type,
                "feature_shard": m.feature_shard}
        elif isinstance(m, FactoredRandomEffectModel):
            sub = os.path.join(directory, "factored-random-effect", name)
            os.makedirs(sub, exist_ok=True)
            np.savez_compressed(
                os.path.join(sub, "coefficients.npz"),
                latent_coefficients=np.asarray(m.latent_coefficients),
                projection=np.asarray(m.projection),
                entity_ids=np.asarray(m.entity_ids).astype(object),
                global_dim=np.asarray(m.global_dim))
            meta["coordinates"][name] = {
                "kind": "factored_random_effect",
                "random_effect_type": m.random_effect_type,
                "feature_shard": m.feature_shard}
        elif isinstance(m, MatrixFactorizationModel):
            # reference: ModelProcessingUtils matrix-factorization save/load
            # (scala:450-516) — row/col latent factors (LatentFactorAvro
            # export lives in data/avro_io.py write_latent_factors_avro)
            sub = os.path.join(directory, "matrix-factorization", name)
            os.makedirs(sub, exist_ok=True)
            np.savez_compressed(
                os.path.join(sub, "factors.npz"),
                row_factors=np.asarray(m.row_factors),
                row_ids=np.asarray(m.row_ids).astype(object),
                col_factors=np.asarray(m.col_factors),
                col_ids=np.asarray(m.col_ids).astype(object))
            meta["coordinates"][name] = {
                "kind": "matrix_factorization",
                "row_effect_type": m.row_effect_type,
                "col_effect_type": m.col_effect_type,
                "task_type": m.task_type}
        else:
            raise TypeError(f"unknown coordinate model type {type(m)}")
    with open(os.path.join(directory, "model-metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)


def load_game_model(directory: str
                    ) -> Tuple[GameModel, Optional[GameTrainingConfig]]:
    """reference: ModelProcessingUtils.loadGameModelFromHDFS (scala:136-238)."""
    with open(os.path.join(directory, "model-metadata.json")) as f:
        meta = json.load(f)
    task = meta["task_type"]
    coords = {}
    for name, info in meta["coordinates"].items():
        if info["kind"] == "fixed_effect":
            z = np.load(os.path.join(directory, "fixed-effect", name,
                                     "coefficients.npz"), allow_pickle=True)
            coeffs = Coefficients(
                jnp.asarray(z["means"]),
                jnp.asarray(z["variances"]) if "variances" in z else None)
            coords[name] = FixedEffectModel(model_for_task(task, coeffs),
                                            info["feature_shard"])
        elif info["kind"] == "factored_random_effect":
            z = np.load(os.path.join(directory, "factored-random-effect", name,
                                     "coefficients.npz"), allow_pickle=True)
            coords[name] = FactoredRandomEffectModel(
                random_effect_type=info["random_effect_type"],
                feature_shard=info["feature_shard"],
                task_type=task,
                latent_coefficients=jnp.asarray(z["latent_coefficients"]),
                projection=jnp.asarray(z["projection"]),
                entity_ids=z["entity_ids"],
                global_dim=int(z["global_dim"]))
        elif info["kind"] == "matrix_factorization":
            z = np.load(os.path.join(directory, "matrix-factorization", name,
                                     "factors.npz"), allow_pickle=True)
            coords[name] = MatrixFactorizationModel(
                row_effect_type=info["row_effect_type"],
                col_effect_type=info["col_effect_type"],
                row_factors=jnp.asarray(z["row_factors"]), row_ids=z["row_ids"],
                col_factors=jnp.asarray(z["col_factors"]), col_ids=z["col_ids"],
                task_type=info.get("task_type", "none"))
        else:
            z = np.load(os.path.join(directory, "random-effect", name,
                                     "coefficients.npz"), allow_pickle=True)
            coords[name] = RandomEffectModel(
                random_effect_type=info["random_effect_type"],
                feature_shard=info["feature_shard"],
                task_type=task,
                coefficients=jnp.asarray(z["coefficients"]),
                entity_ids=z["entity_ids"],
                projection=z["projection"] if "projection" in z else None,
                global_dim=int(z["global_dim"]),
                variances=jnp.asarray(z["variances"]) if "variances" in z else None,
                projection_matrix=(z["projection_matrix"]
                                   if "projection_matrix" in z else None))
    config = (GameTrainingConfig.from_dict(meta["config"])
              if meta.get("config") else None)
    return GameModel(coords, task), config


def save_glm(model, directory: str, index_map: Optional[IndexMap] = None,
             extra_metadata: Optional[dict] = None) -> None:
    """Single-GLM save (reference: legacy GLMSuite.writeModelsToHDFS path)."""
    os.makedirs(directory, exist_ok=True)
    arrays = {"means": np.asarray(model.coefficients.means)}
    if model.coefficients.variances is not None:
        arrays["variances"] = np.asarray(model.coefficients.variances)
    if index_map is not None:
        arrays["feature_keys"] = index_map.index_to_key.astype(object)
    np.savez_compressed(os.path.join(directory, "coefficients.npz"), **arrays)
    with open(os.path.join(directory, "model-metadata.json"), "w") as f:
        json.dump({"format_version": _FORMAT_VERSION,
                   "task_type": type(model).task_type,
                   **(extra_metadata or {})}, f, indent=2)


def load_glm(directory: str):
    with open(os.path.join(directory, "model-metadata.json")) as f:
        meta = json.load(f)
    z = np.load(os.path.join(directory, "coefficients.npz"), allow_pickle=True)
    coeffs = Coefficients(jnp.asarray(z["means"]),
                          jnp.asarray(z["variances"]) if "variances" in z else None)
    return model_for_task(meta["task_type"], coeffs), meta
