"""Model persistence: the checkpoint format.

Rebuild of ModelProcessingUtils (photon-client/.../data/avro/
ModelProcessingUtils.scala:58-669): GAME models persist to a directory tree

    <dir>/model-metadata.json                     # task, config, coordinates
    <dir>/fixed-effect/<name>/coefficients.npz    # means (+variances)
    <dir>/random-effect/<name>/coefficients.npz   # [E, d_local] + projection
                                                  # + entity ids + global dim

mirroring the reference's fixed-effect/<coord>/coefficients/part-*.avro and
random-effect/<coord>/... layout with npz in place of Avro records (an Avro
export for cross-tool parity lives in photon_ml_tpu/data/avro_io.py).
model-metadata.json embeds the full training config JSON exactly like the
reference embeds optimizer configs for scoring-side reproducibility
(ModelProcessingUtils.scala:517-559).  Feature names are stored when an
IndexMap is provided, matching the reference's human-readable name.term
output.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.index_map import (IndexMap, IndexMapCollection,
                                          feature_key)
from photon_ml_tpu.game.config import GameTrainingConfig
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FactoredRandomEffectModel, FixedEffectModel, GameModel,
    MatrixFactorizationModel, RandomEffectModel,
)
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.utils.durable import (atomic_write_json,
                                         atomic_write_text, write_marker)

_FORMAT_VERSION = 1


def _shard_index_map(index_maps, shard, dim) -> IndexMap:
    """The shard's map, or a synthesized zero-padded one (sorted order ==
    column order) when none was recorded — Avro records key features by
    name.term, so SOME map must exist."""
    imap = (index_maps or {}).get(shard)
    if imap is not None:
        return imap
    return IndexMap.from_keys(
        [feature_key(f"{j:09d}") for j in range(dim - 1)], add_intercept=True)


def save_game_model(
    model: GameModel,
    directory: str,
    config: Optional[GameTrainingConfig] = None,
    index_maps: Optional[Dict[str, IndexMap]] = None,
    format: str = "npz",
) -> None:
    """reference: ModelProcessingUtils.saveGameModelsToHDFS (scala:71-135).

    `format="avro"` writes the reference's interchange records instead of
    npz: BayesianLinearModelAvro per fixed-effect model and per random-effect
    entity (original feature space, name.term keys), LatentFactorAvro for
    matrix factorization — a model the Spark implementation can read.
    Factored random effects materialize to per-entity original-space models
    on Avro save (the reference persists original-space models too).

    `model.save` is a fault-injection site (utils/faults.py): chaos runs
    inject write failures here to prove checkpointing surfaces them."""
    from photon_ml_tpu.utils import faults
    faults.fire("model.save", directory=os.path.basename(
        directory.rstrip("/")))
    if format == "avro":
        return _save_game_model_avro(model, directory, config, index_maps)
    if format == "reference":
        return save_game_model_reference_layout(model, directory,
                                                index_maps=index_maps)
    if format != "npz":
        raise ValueError(f"unknown model format {format!r}")
    os.makedirs(directory, exist_ok=True)
    if index_maps:
        IndexMapCollection(dict(index_maps)).save(
            os.path.join(directory, "index-maps"))
    meta = {"format_version": _FORMAT_VERSION, "task_type": model.task_type,
            "coordinates": {}, "config": config.to_dict() if config else None}
    for name, m in model.coordinates.items():
        if isinstance(m, FixedEffectModel):
            sub = os.path.join(directory, "fixed-effect", name)
            os.makedirs(sub, exist_ok=True)
            arrays = {"means": np.asarray(m.glm.coefficients.means)}
            if m.glm.coefficients.variances is not None:
                arrays["variances"] = np.asarray(m.glm.coefficients.variances)
            imap = (index_maps or {}).get(m.feature_shard)
            if imap is not None:
                arrays["feature_keys"] = imap.index_to_key.astype(object)
            np.savez_compressed(os.path.join(sub, "coefficients.npz"), **arrays)
            meta["coordinates"][name] = {"kind": "fixed_effect",
                                         "feature_shard": m.feature_shard}
        elif isinstance(m, RandomEffectModel):
            sub = os.path.join(directory, "random-effect", name)
            os.makedirs(sub, exist_ok=True)
            arrays = {"coefficients": np.asarray(m.coefficients),
                      "entity_ids": np.asarray(m.entity_ids).astype(object),
                      "global_dim": np.asarray(m.global_dim)}
            if m.projection is not None:
                arrays["projection"] = m.projection
            if m.projection_matrix is not None:
                arrays["projection_matrix"] = np.asarray(m.projection_matrix)
            if m.variances is not None:
                arrays["variances"] = np.asarray(m.variances)
            np.savez_compressed(os.path.join(sub, "coefficients.npz"), **arrays)
            meta["coordinates"][name] = {
                "kind": "random_effect",
                "random_effect_type": m.random_effect_type,
                "feature_shard": m.feature_shard}
        elif isinstance(m, FactoredRandomEffectModel):
            sub = os.path.join(directory, "factored-random-effect", name)
            os.makedirs(sub, exist_ok=True)
            np.savez_compressed(
                os.path.join(sub, "coefficients.npz"),
                latent_coefficients=np.asarray(m.latent_coefficients),
                projection=np.asarray(m.projection),
                entity_ids=np.asarray(m.entity_ids).astype(object),
                global_dim=np.asarray(m.global_dim))
            meta["coordinates"][name] = {
                "kind": "factored_random_effect",
                "random_effect_type": m.random_effect_type,
                "feature_shard": m.feature_shard}
        elif isinstance(m, MatrixFactorizationModel):
            # reference: ModelProcessingUtils matrix-factorization save/load
            # (scala:450-516) — row/col latent factors (LatentFactorAvro
            # export lives in data/avro_io.py write_latent_factors_avro)
            sub = os.path.join(directory, "matrix-factorization", name)
            os.makedirs(sub, exist_ok=True)
            np.savez_compressed(
                os.path.join(sub, "factors.npz"),
                row_factors=np.asarray(m.row_factors),
                row_ids=np.asarray(m.row_ids).astype(object),
                col_factors=np.asarray(m.col_factors),
                col_ids=np.asarray(m.col_ids).astype(object))
            meta["coordinates"][name] = {
                "kind": "matrix_factorization",
                "row_effect_type": m.row_effect_type,
                "col_effect_type": m.col_effect_type,
                "task_type": m.task_type}
        else:
            raise TypeError(f"unknown coordinate model type {type(m)}")
    atomic_write_json(os.path.join(directory, "model-metadata.json"), meta)


def _save_game_model_avro(model, directory, config, index_maps) -> None:
    """Avro-format GAME model save (reference interchange artifacts)."""
    from photon_ml_tpu.data.avro_io import (
        write_glm_avro, write_latent_factors_avro, write_random_effect_avro,
    )
    os.makedirs(directory, exist_ok=True)
    meta = {"format_version": _FORMAT_VERSION, "task_type": model.task_type,
            "storage_format": "avro", "coordinates": {},
            "config": config.to_dict() if config else None}
    # every map actually used is persisted — including synthesized ones:
    # Avro records drop zero coefficients, so WITHOUT the map a reload
    # would rebuild a shrunken, shifted feature space
    used_maps: Dict[str, IndexMap] = dict(index_maps or {})
    for name, m in model.coordinates.items():
        if isinstance(m, FixedEffectModel):
            sub = os.path.join(directory, "fixed-effect", name)
            os.makedirs(sub, exist_ok=True)
            means = np.asarray(m.glm.coefficients.means)
            imap = _shard_index_map(index_maps, m.feature_shard, len(means))
            used_maps[m.feature_shard] = imap
            var = m.glm.coefficients.variances
            write_glm_avro(os.path.join(sub, "coefficients.avro"), name,
                           model.task_type, means, imap,
                           None if var is None else np.asarray(var))
            meta["coordinates"][name] = {"kind": "fixed_effect",
                                         "feature_shard": m.feature_shard}
        elif isinstance(m, (RandomEffectModel, FactoredRandomEffectModel)):
            factored = isinstance(m, FactoredRandomEffectModel)
            re = m.to_random_effect_model() if factored else m
            if re.projection_matrix is not None:
                # random-projection RE: Avro records key coefficients by
                # ORIGINAL-space feature; write P^T c, not the projected-space
                # slots (which would alias local slot j to feature j).
                # Projected-space variances have no per-feature meaning and
                # are dropped, like the factored path.
                re = RandomEffectModel(
                    random_effect_type=re.random_effect_type,
                    feature_shard=re.feature_shard, task_type=re.task_type,
                    coefficients=re.global_coefficients(),
                    entity_ids=re.entity_ids, projection=None,
                    global_dim=re.global_dim)
            sub = os.path.join(directory, "random-effect", name)
            os.makedirs(sub, exist_ok=True)
            imap = _shard_index_map(index_maps, re.feature_shard,
                                    re.global_dim)
            used_maps[re.feature_shard] = imap
            write_random_effect_avro(
                os.path.join(sub, "coefficients.avro"), model.task_type,
                re.entity_ids, np.asarray(re.coefficients), imap,
                projection=re.projection,
                variances=(None if re.variances is None
                           else np.asarray(re.variances)))
            if factored:
                # the latent decomposition itself, as LatentFactorAvro
                write_latent_factors_avro(
                    os.path.join(sub, "latent-projection.avro"),
                    [str(k) for k in range(m.latent_dim)],
                    np.asarray(m.projection))
                write_latent_factors_avro(
                    os.path.join(sub, "latent-coefficients.avro"),
                    [str(e) for e in np.asarray(m.entity_ids)],
                    np.asarray(m.latent_coefficients))
            meta["coordinates"][name] = {
                "kind": "random_effect",
                "random_effect_type": re.random_effect_type,
                "feature_shard": re.feature_shard,
                **({"materialized_from": "factored_random_effect"}
                   if factored else {})}
        elif isinstance(m, MatrixFactorizationModel):
            from photon_ml_tpu.data.avro_io import write_latent_factors_avro
            sub = os.path.join(directory, "matrix-factorization", name)
            os.makedirs(sub, exist_ok=True)
            write_latent_factors_avro(os.path.join(sub, "row-factors.avro"),
                                      [str(i) for i in np.asarray(m.row_ids)],
                                      np.asarray(m.row_factors))
            write_latent_factors_avro(os.path.join(sub, "col-factors.avro"),
                                      [str(i) for i in np.asarray(m.col_ids)],
                                      np.asarray(m.col_factors))
            meta["coordinates"][name] = {
                "kind": "matrix_factorization",
                "row_effect_type": m.row_effect_type,
                "col_effect_type": m.col_effect_type,
                "task_type": m.task_type}
        else:
            raise TypeError(f"unknown coordinate model type {type(m)}")
    if used_maps:
        IndexMapCollection(used_maps).save(
            os.path.join(directory, "index-maps"))
    atomic_write_json(os.path.join(directory, "model-metadata.json"), meta)


def load_model_index_maps(directory: str) -> Optional[Dict[str, IndexMap]]:
    """The per-shard feature maps recorded at save time (needed to read
    scoring/validation Avro data in the model's feature space).  For a
    reference-layout directory nothing was recorded, but the maps are fully
    determined by the model records themselves (compact scan order,
    reference: AvroUtils.makeFeatureIndexForModel), so they are rebuilt."""
    path = os.path.join(directory, "index-maps")
    if os.path.isdir(path):
        return IndexMapCollection.load(path).shards
    if _is_reference_layout(directory):
        return _reference_layout_index_maps(directory)
    return None


# -- the Scala reference's own on-disk layout --------------------------------
#
# reference: ModelProcessingUtils.scala:71-135 (save) / :136-238 (load):
#
#   <dir>/model-metadata.json                      # {"modelType": "...", ...}
#   <dir>/fixed-effect/<name>/id-info              # 1 line: featureShardId
#   <dir>/fixed-effect/<name>/coefficients/part-00000.avro
#   <dir>/random-effect/<name>/id-info             # 2 lines: REType, shardId
#   <dir>/random-effect/<name>/coefficients/part-*.avro  (+ _SUCCESS marker)
#
# Coefficients are BayesianLinearModelAvro records; random-effect containers
# hold one record per entity (modelId = entity id), split across Spark
# partition files.

_REFERENCE_TASKS = {
    "LOGISTIC_REGRESSION": "logistic_regression",
    "LINEAR_REGRESSION": "linear_regression",
    "POISSON_REGRESSION": "poisson_regression",
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": "smoothed_hinge_loss_linear_svm",
    "NONE": None,
}


def _is_reference_layout(directory: str) -> bool:
    meta_p = os.path.join(directory, "model-metadata.json")
    if os.path.exists(meta_p):
        try:
            with open(meta_p) as f:
                meta = json.load(f)
        except ValueError:
            return False
        return "modelType" in meta and "coordinates" not in meta
    # pre-metadata reference models: recognized by the id-info files
    for kind in ("fixed-effect", "random-effect"):
        base = os.path.join(directory, kind)
        if os.path.isdir(base):
            for name in os.listdir(base):
                if os.path.exists(os.path.join(base, name, "id-info")):
                    return True
    return False


def _reference_coordinate_dirs(directory: str):
    """-> [(kind, name, shard, re_type, part_files)] sorted by name."""
    out = []
    for kind in ("fixed-effect", "random-effect"):
        base = os.path.join(directory, kind)
        if not os.path.isdir(base):
            continue
        for name in sorted(os.listdir(base)):
            sub = os.path.join(base, name)
            id_info = os.path.join(sub, "id-info")
            coeff_dir = os.path.join(sub, "coefficients")
            if not os.path.isdir(coeff_dir):
                continue
            if not os.path.exists(id_info):
                raise ValueError(
                    f"{sub}: reference-layout coordinate has coefficients "
                    "but no id-info file (expected 1 line for fixed-effect: "
                    "featureShardId; 2 for random-effect: randomEffectType, "
                    "featureShardId)")
            with open(id_info) as f:
                ids = [ln.strip() for ln in f if ln.strip()]
            expected = 1 if kind == "fixed-effect" else 2
            if len(ids) != expected:
                raise ValueError(
                    f"{id_info}: expected {expected} line(s) for a "
                    f"{kind} coordinate, got {len(ids)}: {ids!r}")
            if kind == "fixed-effect":
                (shard,), re_type = ids, None
            else:
                re_type, shard = ids
            parts = sorted(
                os.path.join(coeff_dir, fn) for fn in os.listdir(coeff_dir)
                if not fn.startswith(("_", ".")))
            if not parts:
                raise ValueError(f"{coeff_dir}: no coefficient part files")
            out.append((kind, name, shard, re_type, parts))
    if not out:
        raise ValueError(
            f"no models could be loaded from reference-layout {directory!r}")
    return out


def _maps_from_coordinate_records(coord_recs) -> Dict[str, IndexMap]:
    """One map per feature shard, built from the union of every
    coordinate's record keys on that shard — coordinates sharing a shard
    share one map, so loaded coefficient columns can never disagree."""
    from photon_ml_tpu.data.avro_io import model_record_keys
    keys_by_shard: Dict[str, list] = {}
    for (_, _, shard, _, _), recs in coord_recs:
        keys_by_shard.setdefault(shard, []).extend(model_record_keys(recs))
    return {shard: IndexMap.from_keys(
                [feature_key(n, t) for n, t in keys], add_intercept=True)
            for shard, keys in keys_by_shard.items()}


_REF_MAPS_MEMO: dict = {}


def _reference_dir_stamp(directory: str, entries) -> tuple:
    """On-disk identity of a reference model dir: every part file AND every
    id-info file (sizes + mtimes)."""
    files = [p for _, _, _, _, parts in entries for p in parts]
    for kind, name, _, _, _ in entries:
        files.append(os.path.join(directory, kind, name, "id-info"))
    return tuple((p, os.path.getsize(p), os.stat(p).st_mtime_ns)
                 for p in files)


def _memoized_reference_maps(directory, entries, coord_recs=None):
    """The rebuilt per-shard maps, memoized per on-disk state so a scoring
    run (load_game_model + load_model_index_maps) decodes every part file
    once, not twice.  Only the LIGHT maps are retained — record lists are
    never cached, so a loaded multi-million-entity model is not held
    resident twice."""
    from photon_ml_tpu.data.avro_io import _read_model_records
    stamp = _reference_dir_stamp(directory, entries)
    key = os.path.abspath(directory)
    cached = _REF_MAPS_MEMO.get(key)
    if cached is not None and cached[0] == stamp:
        return cached[1]
    if coord_recs is None:
        coord_recs = [(entry, _read_model_records(entry[4]))
                      for entry in entries]
    maps = _maps_from_coordinate_records(coord_recs)
    _REF_MAPS_MEMO.clear()  # keep at most one directory resident
    _REF_MAPS_MEMO[key] = (stamp, maps)
    return maps


def _reference_layout_index_maps(directory: str) -> Dict[str, IndexMap]:
    return _memoized_reference_maps(directory,
                                    _reference_coordinate_dirs(directory))


def _load_game_model_reference(
    directory: str,
    index_maps: Optional[Dict[str, IndexMap]] = None,
) -> Tuple[GameModel, None]:
    """Load a GAME model the Scala reference itself wrote
    (ModelProcessingUtils.scala:136-238).  Without provided index maps the
    feature spaces are rebuilt compactly from the records, exactly like the
    reference's makeFeatureIndexForModel path."""
    from photon_ml_tpu.data.avro_io import (_TASK_BY_CLASS,
                                            glm_arrays_from_record,
                                            re_arrays_from_records)
    meta_task = None
    meta_p = os.path.join(directory, "model-metadata.json")
    if os.path.exists(meta_p):
        with open(meta_p) as f:
            raw = json.load(f)
        model_type = str(raw.get("modelType", "NONE"))
        if model_type not in _REFERENCE_TASKS:
            raise ValueError(f"unknown reference modelType {model_type!r}")
        meta_task = _REFERENCE_TASKS[model_type]
    from photon_ml_tpu.data.avro_io import _read_model_records
    entries = _reference_coordinate_dirs(directory)
    coord_recs = [(entry, _read_model_records(entry[4]))
                  for entry in entries]
    if index_maps is None:
        # prefer maps saved next to the model (our own reference-layout
        # writer records them so L1-zeroed coefficients keep their columns);
        # a directory the Scala reference wrote has none -> rebuild compactly
        saved = os.path.join(directory, "index-maps")
        index_maps = (IndexMapCollection.load(saved).shards
                      if os.path.isdir(saved)
                      else _memoized_reference_maps(directory, entries,
                                                    coord_recs))
    coords = {}
    tasks = set()
    for (kind, name, shard, re_type, _), recs in coord_recs:
        imap = index_maps[shard]
        if kind == "fixed-effect":
            if len(recs) != 1:
                raise ValueError(
                    f"{directory}/{kind}/{name}: expected one fixed-effect "
                    f"record, got {len(recs)}")
            _, task, means, variances = glm_arrays_from_record(recs[0], imap)
            coords[name] = (task, "fe", shard, means, variances)
        else:
            e_ids, means, variances = re_arrays_from_records(recs, imap)
            task = (_TASK_BY_CLASS.get(recs[0].get("modelClass") or "", None)
                    if recs else None)  # empty Spark partitions are normal
            coords[name] = (task, "re", shard, (e_ids, means, variances),
                            re_type)
        if task:
            tasks.add(task)
    task_type = meta_task or (tasks.pop() if len(tasks) == 1 else None)
    if task_type is None:
        raise ValueError(
            f"cannot determine task type for {directory!r}: no modelType "
            "metadata and no modelClass on the records")
    out = {}
    for name, info in coords.items():
        if info[1] == "fe":
            _, _, shard, means, variances = info
            coeffs = Coefficients(
                jnp.asarray(means),
                None if variances is None else jnp.asarray(variances))
            out[name] = FixedEffectModel(model_for_task(task_type, coeffs),
                                         shard)
        else:
            _, _, shard, (e_ids, means, variances), re_type = info
            out[name] = RandomEffectModel(
                random_effect_type=re_type, feature_shard=shard,
                task_type=task_type, coefficients=jnp.asarray(means),
                entity_ids=np.asarray(e_ids, dtype=object),
                projection=None, global_dim=means.shape[1],
                variances=(None if variances is None
                           else jnp.asarray(variances)))
    return GameModel(out, task_type), None


def save_game_model_reference_layout(
    model: GameModel,
    directory: str,
    index_maps: Optional[Dict[str, IndexMap]] = None,
    num_re_partitions: int = 1,
) -> None:
    """Write a GAME model in the Scala reference's OWN directory layout
    (ModelProcessingUtils.scala:71-135), so actual photon-ml can score or
    warm-start from it.  Factored/random-projection random effects
    materialize to original space; matrix-factorization coordinates are
    rejected (the reference stores MF models separately, scala:450-516)."""
    from photon_ml_tpu.data.avro_io import (write_glm_avro,
                                            write_random_effect_avro)
    os.makedirs(directory, exist_ok=True)
    if index_maps:
        # Avro records drop zero coefficients (L1 makes exact zeros
        # common), so without the maps a reload rebuilds a shrunken,
        # shifted feature space.  The extra index-maps/ dir is ours; the
        # Scala reference ignores unknown directories.
        IndexMapCollection(dict(index_maps)).save(
            os.path.join(directory, "index-maps"))
    atomic_write_json(
        os.path.join(directory, "model-metadata.json"),
        {"modelType": {v: k for k, v in _REFERENCE_TASKS.items()
                       if v}.get(model.task_type, "NONE"),
         "modelName": os.path.basename(directory.rstrip("/"))})
    for name, m in model.coordinates.items():
        if isinstance(m, MatrixFactorizationModel):
            raise ValueError(
                "matrix-factorization coordinates have no reference GAME "
                "model layout (saved separately in the reference, "
                "ModelProcessingUtils.scala:450-516)")
        if isinstance(m, FactoredRandomEffectModel):
            m = m.to_random_effect_model()
        if isinstance(m, FixedEffectModel):
            sub = os.path.join(directory, "fixed-effect", name)
            coeff_dir = os.path.join(sub, "coefficients")
            os.makedirs(coeff_dir, exist_ok=True)
            atomic_write_text(os.path.join(sub, "id-info"),
                              m.feature_shard + "\n")
            means = np.asarray(m.glm.coefficients.means)
            imap = (index_maps or {}).get(m.feature_shard) or \
                _shard_index_map(None, m.feature_shard, len(means))
            var = m.glm.coefficients.variances
            # modelId is the literal "fixed-effect", matching the Scala
            # writer (saveModelToHDFS passes AvroConstants.FIXED_EFFECT)
            write_glm_avro(
                os.path.join(coeff_dir, "part-00000.avro"), "fixed-effect",
                model.task_type, means, imap,
                None if var is None else np.asarray(var))
        elif isinstance(m, RandomEffectModel):
            if m.projection_matrix is not None:
                m = RandomEffectModel(
                    random_effect_type=m.random_effect_type,
                    feature_shard=m.feature_shard, task_type=m.task_type,
                    coefficients=m.global_coefficients(),
                    entity_ids=m.entity_ids, projection=None,
                    global_dim=m.global_dim)
            sub = os.path.join(directory, "random-effect", name)
            coeff_dir = os.path.join(sub, "coefficients")
            os.makedirs(coeff_dir, exist_ok=True)
            atomic_write_text(os.path.join(sub, "id-info"),
                              m.random_effect_type + "\n"
                              + m.feature_shard + "\n")
            imap = (index_maps or {}).get(m.feature_shard) or \
                _shard_index_map(None, m.feature_shard, m.global_dim)
            E = m.num_entities
            n_parts = max(1, min(num_re_partitions, E))
            bounds = np.linspace(0, E, n_parts + 1).astype(int)
            for p in range(n_parts):
                lo, hi = int(bounds[p]), int(bounds[p + 1])
                write_random_effect_avro(
                    os.path.join(coeff_dir, f"part-{p:05d}.avro"),
                    m.task_type, np.asarray(m.entity_ids)[lo:hi],
                    np.asarray(m.coefficients)[lo:hi], imap,
                    projection=(None if m.projection is None
                                else m.projection[lo:hi]),
                    variances=(None if m.variances is None
                               else np.asarray(m.variances)[lo:hi]))
            # Spark leaves a _SUCCESS marker; the loader must skip it
            write_marker(os.path.join(coeff_dir, "_SUCCESS"))
        else:
            raise TypeError(f"unknown coordinate model type {type(m)}")


def _load_game_model_avro(directory, meta):
    from photon_ml_tpu.data.avro_io import (
        read_glm_avro, read_latent_factors_avro, read_random_effect_avro,
    )
    task = meta["task_type"]
    saved_maps = load_model_index_maps(directory) or {}
    coords = {}
    for name, info in meta["coordinates"].items():
        if info["kind"] == "fixed_effect":
            _, _, means, variances, _ = read_glm_avro(
                os.path.join(directory, "fixed-effect", name,
                             "coefficients.avro"),
                saved_maps.get(info["feature_shard"]))
            coeffs = Coefficients(
                jnp.asarray(means),
                None if variances is None else jnp.asarray(variances))
            coords[name] = FixedEffectModel(model_for_task(task, coeffs),
                                            info["feature_shard"])
        elif info["kind"] == "random_effect":
            e_ids, means, variances, imap = read_random_effect_avro(
                os.path.join(directory, "random-effect", name,
                             "coefficients.avro"),
                saved_maps.get(info["feature_shard"]))
            coords[name] = RandomEffectModel(
                random_effect_type=info["random_effect_type"],
                feature_shard=info["feature_shard"], task_type=task,
                coefficients=jnp.asarray(means),
                entity_ids=np.asarray(e_ids, dtype=object),
                projection=None, global_dim=imap.size,
                variances=(None if variances is None
                           else jnp.asarray(variances)))
        elif info["kind"] == "matrix_factorization":
            sub = os.path.join(directory, "matrix-factorization", name)
            row_ids, row_f = read_latent_factors_avro(
                os.path.join(sub, "row-factors.avro"))
            col_ids, col_f = read_latent_factors_avro(
                os.path.join(sub, "col-factors.avro"))
            coords[name] = MatrixFactorizationModel(
                row_effect_type=info["row_effect_type"],
                col_effect_type=info["col_effect_type"],
                row_factors=jnp.asarray(row_f),
                row_ids=np.asarray(row_ids, dtype=object),
                col_factors=jnp.asarray(col_f),
                col_ids=np.asarray(col_ids, dtype=object),
                task_type=info.get("task_type", "none"))
        else:
            raise ValueError(
                f"unknown avro coordinate kind {info['kind']!r}")
    config = (GameTrainingConfig.from_dict(meta["config"])
              if meta.get("config") else None)
    return GameModel(coords, task), config


def load_game_model(directory: str
                    ) -> Tuple[GameModel, Optional[GameTrainingConfig]]:
    """reference: ModelProcessingUtils.loadGameModelFromHDFS (scala:136-238).

    Accepts this package's npz and Avro layouts AND a model directory the
    Scala reference itself wrote (part-*.avro partition files + the
    reference's own model-metadata.json, or no metadata at all for
    pre-metadata models).

    `model.load` is a fault-injection site (utils/faults.py): chaos runs
    inject read failures here to prove resume falls back cleanly."""
    from photon_ml_tpu.utils import faults
    faults.fire("model.load", directory=os.path.basename(
        directory.rstrip("/")))
    meta_p = os.path.join(directory, "model-metadata.json")
    if not os.path.exists(meta_p):
        if _is_reference_layout(directory):
            return _load_game_model_reference(directory)
        raise FileNotFoundError(meta_p)
    with open(meta_p) as f:
        meta = json.load(f)
    if "modelType" in meta and "coordinates" not in meta:
        return _load_game_model_reference(directory)
    if meta.get("storage_format") == "avro":
        return _load_game_model_avro(directory, meta)
    task = meta["task_type"]
    coords = {}
    for name, info in meta["coordinates"].items():
        if info["kind"] == "fixed_effect":
            z = np.load(os.path.join(directory, "fixed-effect", name,
                                     "coefficients.npz"), allow_pickle=True)
            coeffs = Coefficients(
                jnp.asarray(z["means"]),
                jnp.asarray(z["variances"]) if "variances" in z else None)
            coords[name] = FixedEffectModel(model_for_task(task, coeffs),
                                            info["feature_shard"])
        elif info["kind"] == "factored_random_effect":
            z = np.load(os.path.join(directory, "factored-random-effect", name,
                                     "coefficients.npz"), allow_pickle=True)
            coords[name] = FactoredRandomEffectModel(
                random_effect_type=info["random_effect_type"],
                feature_shard=info["feature_shard"],
                task_type=task,
                latent_coefficients=jnp.asarray(z["latent_coefficients"]),
                projection=jnp.asarray(z["projection"]),
                entity_ids=z["entity_ids"],
                global_dim=int(z["global_dim"]))
        elif info["kind"] == "matrix_factorization":
            z = np.load(os.path.join(directory, "matrix-factorization", name,
                                     "factors.npz"), allow_pickle=True)
            coords[name] = MatrixFactorizationModel(
                row_effect_type=info["row_effect_type"],
                col_effect_type=info["col_effect_type"],
                row_factors=jnp.asarray(z["row_factors"]), row_ids=z["row_ids"],
                col_factors=jnp.asarray(z["col_factors"]), col_ids=z["col_ids"],
                task_type=info.get("task_type", "none"))
        else:
            z = np.load(os.path.join(directory, "random-effect", name,
                                     "coefficients.npz"), allow_pickle=True)
            coords[name] = RandomEffectModel(
                random_effect_type=info["random_effect_type"],
                feature_shard=info["feature_shard"],
                task_type=task,
                coefficients=jnp.asarray(z["coefficients"]),
                entity_ids=z["entity_ids"],
                projection=z["projection"] if "projection" in z else None,
                global_dim=int(z["global_dim"]),
                variances=jnp.asarray(z["variances"]) if "variances" in z else None,
                projection_matrix=(z["projection_matrix"]
                                   if "projection_matrix" in z else None))
    config = (GameTrainingConfig.from_dict(meta["config"])
              if meta.get("config") else None)
    return GameModel(coords, task), config


def _remap_columns(arr: np.ndarray, source: IndexMap,
                   target: IndexMap) -> np.ndarray:
    """Re-key the last axis from `source`'s column order to `target`'s;
    features absent from the source become 0."""
    idx = np.asarray([source.key_to_index.get(str(k), -1)
                      for k in target.index_to_key])
    gathered = np.asarray(arr)[..., np.maximum(idx, 0)]
    return np.where(idx >= 0, gathered, 0.0)


def align_game_model_to_dataset(model: GameModel,
                                model_maps: Optional[Dict[str, IndexMap]],
                                dataset) -> GameModel:
    """Make a loaded model usable as a warm start for `dataset`: remap each
    coordinate's coefficients into the dataset's feature spaces.

    A reference-layout model rebuilds a COMPACT feature space from its
    records (zero coefficients are not stored), and a different data slice
    scans a different vocabulary — warm-starting raw coefficients would
    either shape-error or silently bind them to the wrong features.  With
    index maps on both sides, columns re-key by (name, term) and missing
    features start at 0; without maps, the dimensions must match exactly.
    Projected/factored/matrix-factorization coordinates cannot re-key
    (their local spaces don't carry global feature names) and require
    identical dimensions."""
    import dataclasses
    import jax.numpy as jnp
    model_maps = model_maps or {}
    out = {}
    for name, m in model.coordinates.items():
        if isinstance(m, FixedEffectModel):
            shard = m.feature_shard
            if shard not in dataset.feature_shards:
                raise ValueError(
                    f"warm-start coordinate {name!r} scores feature shard "
                    f"{shard!r}, which the training data does not carry")
            want = dataset.feature_shards[shard].shape[1]
            means = np.asarray(m.glm.coefficients.means)
            mm, tm = model_maps.get(shard), dataset.index_maps.get(shard)
            if mm is not None and tm is not None and \
                    list(mm.index_to_key) != list(tm.index_to_key):
                var = m.glm.coefficients.variances
                coeffs = Coefficients(
                    jnp.asarray(_remap_columns(means, mm, tm)),
                    None if var is None else
                    jnp.asarray(_remap_columns(np.asarray(var), mm, tm)))
                m = FixedEffectModel(
                    m.glm.with_coefficients(coeffs), shard)
            elif len(means) != want:
                raise ValueError(
                    f"warm-start coordinate {name!r} has {len(means)} "
                    f"coefficients but shard {shard!r} is {want} wide, and "
                    "no index maps exist on both sides to re-key them by "
                    "feature name")
            out[name] = m
            continue
        if isinstance(m, RandomEffectModel) and m.projection is None \
                and m.projection_matrix is None:
            shard = m.feature_shard
            want = dataset.feature_shards.get(shard)
            want = None if want is None else want.shape[1]
            coefs = np.asarray(m.coefficients)
            mm, tm = model_maps.get(shard), dataset.index_maps.get(shard)
            if mm is not None and tm is not None and \
                    list(mm.index_to_key) != list(tm.index_to_key):
                m = dataclasses.replace(
                    m, coefficients=jnp.asarray(_remap_columns(coefs, mm, tm)),
                    variances=None if m.variances is None else
                    jnp.asarray(_remap_columns(np.asarray(m.variances),
                                               mm, tm)),
                    global_dim=tm.size)
            elif want is not None and coefs.shape[1] != want:
                raise ValueError(
                    f"warm-start coordinate {name!r} has width "
                    f"{coefs.shape[1]} but shard {shard!r} is {want} wide, "
                    "and no index maps exist on both sides to re-key")
            out[name] = m
            continue
        # projected / factored / MF coordinates: no global names to re-key
        shard = getattr(m, "feature_shard", None)
        if shard is not None and shard in dataset.feature_shards:
            want = dataset.feature_shards[shard].shape[1]
            have = getattr(m, "global_dim", want)
            if have != want:
                raise ValueError(
                    f"warm-start coordinate {name!r} ({type(m).__name__}) "
                    f"lives in a projected space over a {have}-wide shard, "
                    f"but the training shard {shard!r} is {want} wide — "
                    "projected coordinates cannot be re-keyed")
        out[name] = m
    return GameModel(out, model.task_type)


def save_glm(model, directory: str, index_map: Optional[IndexMap] = None,
             extra_metadata: Optional[dict] = None) -> None:
    """Single-GLM save (reference: legacy GLMSuite.writeModelsToHDFS path)."""
    os.makedirs(directory, exist_ok=True)
    arrays = {"means": np.asarray(model.coefficients.means)}
    if model.coefficients.variances is not None:
        arrays["variances"] = np.asarray(model.coefficients.variances)
    if index_map is not None:
        arrays["feature_keys"] = index_map.index_to_key.astype(object)
    np.savez_compressed(os.path.join(directory, "coefficients.npz"), **arrays)
    atomic_write_json(os.path.join(directory, "model-metadata.json"),
                      {"format_version": _FORMAT_VERSION,
                       "task_type": type(model).task_type,
                       **(extra_metadata or {})})


def load_glm(directory: str):
    with open(os.path.join(directory, "model-metadata.json")) as f:
        meta = json.load(f)
    z = np.load(os.path.join(directory, "coefficients.npz"), allow_pickle=True)
    coeffs = Coefficients(jnp.asarray(z["means"]),
                          jnp.asarray(z["variances"]) if "variances" in z else None)
    return model_for_task(meta["task_type"], coeffs), meta


# -- online model deltas ------------------------------------------------------

def save_model_delta(delta, directory: str) -> None:
    """Durable persistence of an online ModelDelta (photon_ml_tpu/online):
    the audit/replication artifact of a row-level delta swap.

    Write discipline matches checkpoints: the npz lands via tmp + fsync +
    atomic replace, metadata via atomic_write_json, and a per-file
    size+sha256 manifest.json is written LAST — at any crash instant the
    directory either verifies complete or is detectably partial
    (load_model_delta refuses the latter)."""
    from photon_ml_tpu.utils.durable import (fsync_dir, fsync_file,
                                             write_manifest)
    os.makedirs(directory, exist_ok=True)
    npz_path = os.path.join(directory, "delta.npz")
    tmp = npz_path + ".tmp.npz"   # savez appends .npz to unsuffixed paths
    np.savez_compressed(tmp, **delta.to_arrays())
    fsync_file(tmp)
    os.replace(tmp, npz_path)
    fsync_dir(directory)
    atomic_write_json(os.path.join(directory, "delta-metadata.json"), {
        "format_version": _FORMAT_VERSION,
        "base_version": delta.base_version,
        "delta_seq": delta.seq,
        "created_at": delta.created_at,
        "coordinates": {name: cd.num_rows
                        for name, cd in delta.coordinates.items()},
        "num_rows": delta.num_rows,
    })
    write_manifest(directory)


def load_model_delta(directory: str):
    """Load + VERIFY a persisted ModelDelta: the manifest must be present
    and every file must match its recorded size and sha256 (a torn or
    tampered delta must never reach apply_delta)."""
    from photon_ml_tpu.online.delta import ModelDelta
    from photon_ml_tpu.utils.durable import file_sha256
    manifest_p = os.path.join(directory, "manifest.json")
    if not os.path.exists(manifest_p):
        raise FileNotFoundError(
            f"no manifest.json in {directory!r} — the delta write did not "
            "complete (or this is not a delta directory)")
    with open(manifest_p) as f:
        manifest = json.load(f)
    for rel, want in manifest.get("files", {}).items():
        p = os.path.join(directory, rel)
        if not os.path.exists(p) or os.path.getsize(p) != want["bytes"] \
                or file_sha256(p) != want["sha256"]:
            raise ValueError(
                f"delta file {rel!r} in {directory!r} does not match its "
                "manifest (size/sha256) — refusing to load a torn or "
                "tampered delta")
    with open(os.path.join(directory, "delta-metadata.json")) as f:
        meta = json.load(f)
    z = np.load(os.path.join(directory, "delta.npz"), allow_pickle=True)
    return ModelDelta.from_arrays(
        {k: z[k] for k in z.files}, base_version=meta["base_version"],
        seq=meta["delta_seq"], created_at=meta.get("created_at", 0.0))
