"""Composable trained-model validity checks.

Rebuild of the reference's ModelValidator suite (photon-api/src/integTest/
.../supervised/{ModelValidator, PredictionFiniteValidator,
BinaryPredictionValidator, NonNegativePredictionValidator,
MaximumDifferenceValidator, BinaryClassifierAUCValidator,
CompositeModelValidator}.scala): after training, assert that a model's
predictions over a dataset are sane — finite, in-range for the task,
within an error bound, above a minimum AUC — and raise with a count of
offending rows otherwise.  The reference filters RDDs per check; here each
check is one vectorized pass over the prediction array, and a composite
runs every check on a single shared prediction computation.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.models.glm import GeneralizedLinearModel, _BinaryClassifier

#: reference: MathConst.POSITIVE_RESPONSE_THRESHOLD
POSITIVE_RESPONSE_THRESHOLD = 0.5


class ModelValidationError(ValueError):
    """A trained model failed a validity check (reference raises
    IllegalStateException)."""


def _predictions(model: GeneralizedLinearModel, x, offsets=None) -> np.ndarray:
    """Mean predictions (inverse link), one device round trip shared by
    every check in a composite."""
    return np.asarray(model.predict(x, offsets))


@dataclasses.dataclass(frozen=True)
class PredictionFiniteValidator:
    """reference: PredictionFiniteValidator — no NaN/±Inf predictions."""

    def validate(self, model, x, labels=None, offsets=None,
                 predictions: Optional[np.ndarray] = None) -> None:
        p = _predictions(model, x, offsets) if predictions is None else predictions
        bad = int((~np.isfinite(p)).sum())
        if bad:
            raise ModelValidationError(
                f"found [{bad}] samples with invalid (NaN or +/-Inf) "
                "predictions")


@dataclasses.dataclass(frozen=True)
class BinaryPredictionValidator:
    """reference: BinaryPredictionValidator — class predictions at the
    positive-response threshold must be exactly 0 or 1."""

    threshold: float = POSITIVE_RESPONSE_THRESHOLD

    def validate(self, model, x, labels=None, offsets=None,
                 predictions=None) -> None:
        if not isinstance(model, _BinaryClassifier):
            raise ModelValidationError(
                f"binary-prediction validation requires a classifier, got "
                f"{type(model).__name__}")
        if predictions is not None and type(model).predict_class is \
                _BinaryClassifier.predict_class:
            # mean-threshold classifiers derive classes from the shared
            # prediction array; only margin-threshold overrides (the
            # smoothed-hinge SVM) need their own pass
            cls = (np.asarray(predictions) >= self.threshold).astype(int)
        else:
            cls = np.asarray(model.predict_class(x, offsets,
                                                 threshold=self.threshold))
        bad = int(((cls != 0.0) & (cls != 1.0)).sum())
        if bad:
            raise ModelValidationError(
                f"found [{bad}] samples with invalid class predictions "
                "(expected 0 or 1)")


@dataclasses.dataclass(frozen=True)
class NonNegativePredictionValidator:
    """reference: NonNegativePredictionValidator / PredictionNonNegative —
    predictions must be >= 0 (Poisson means, probabilities, counts)."""

    def validate(self, model, x, labels=None, offsets=None,
                 predictions=None) -> None:
        p = _predictions(model, x, offsets) if predictions is None else predictions
        bad = int((p < 0).sum())
        if bad:
            raise ModelValidationError(
                f"found [{bad}] samples with invalid negative predictions")


@dataclasses.dataclass(frozen=True)
class MaximumDifferenceValidator:
    """reference: MaximumDifferenceValidator — |prediction - label| must
    not exceed `maximum_difference` on any row."""

    maximum_difference: float

    def __post_init__(self):
        if not self.maximum_difference > 0:
            raise ValueError("maximum_difference must be > 0")

    def validate(self, model, x, labels, offsets=None,
                 predictions=None) -> None:
        p = _predictions(model, x, offsets) if predictions is None else predictions
        bad = int((np.abs(p - np.asarray(labels))
                   > self.maximum_difference).sum())
        if bad:
            raise ModelValidationError(
                f"found [{bad}] instances where the magnitude of the "
                f"prediction error is greater than "
                f"[{self.maximum_difference}]")


@dataclasses.dataclass(frozen=True)
class BinaryClassifierAUCValidator:
    """reference: BinaryClassifierAUCValidator — AUROC of mean predictions
    (with offsets) must reach `minimum_auc`."""

    minimum_auc: float

    def __post_init__(self):
        if not 0.5 <= self.minimum_auc <= 1.0:
            raise ValueError("minimum_auc must be in [0.5, 1.0]")

    def validate(self, model, x, labels, offsets=None,
                 predictions=None) -> None:
        from photon_ml_tpu.evaluation.evaluators import AUC
        p = _predictions(model, x, offsets) if predictions is None else predictions
        auc = AUC(p, np.asarray(labels))
        if not auc >= self.minimum_auc:  # NaN AUC fails too
            raise ModelValidationError(
                f"computed AUROC [{auc}] is smaller than minimum required "
                f"[{self.minimum_auc}]")


_NEEDS_LABELS = (MaximumDifferenceValidator, BinaryClassifierAUCValidator)


@dataclasses.dataclass(frozen=True)
class CompositeModelValidator:
    """reference: CompositeModelValidator — run every check in order; the
    mean-prediction array is computed once and shared.  Accepts either an
    iterable of validators or them as positional args."""

    validators: Sequence[object]

    def __init__(self, *validators, **kw):
        # CompositeModelValidator(v1, v2), CompositeModelValidator([v1, v2])
        # and dataclasses.replace(c, validators=[...]) all work
        if kw:
            if validators or set(kw) != {"validators"}:
                raise TypeError(
                    "pass validators positionally, as one iterable, or as "
                    "the 'validators' keyword")
            validators = tuple(kw["validators"])
        elif len(validators) == 1 and not hasattr(validators[0], "validate"):
            validators = tuple(validators[0])
        object.__setattr__(self, "validators", tuple(validators))

    def validate(self, model, x, labels=None, offsets=None,
                 predictions=None) -> None:
        if labels is None:
            needy = [type(v).__name__ for v in self.validators
                     if isinstance(v, _NEEDS_LABELS)]
            if needy:
                raise ModelValidationError(
                    f"validators {needy} require labels")
        if predictions is None:
            predictions = _predictions(model, x, offsets)
        for v in self.validators:
            v.validate(model, x, labels, offsets, predictions=predictions)
