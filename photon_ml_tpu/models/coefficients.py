"""Model coefficients (means + optional variances).

reference: photon-lib/.../model/Coefficients.scala:31-168.
A pytree so models flow through jit/vmap; `variances` comes from the
Hessian-diagonal estimate (reference: DistributedOptimizationProblem
.computeVariances:80-95).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.utils.math import EPSILON


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Coefficients:
    means: jax.Array
    variances: Optional[jax.Array] = None

    def tree_flatten(self):
        return (self.means, self.variances), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, x) -> jax.Array:
        """x may be [d] or a feature matrix [n, d] (dense, BCOO, or
        PaddedSparse).  reference: Coefficients.computeScore
        (Coefficients.scala:53)."""
        if x.ndim == 1:
            return x @ self.means
        from photon_ml_tpu.ops import features as fops
        return fops.matvec(x, self.means)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(jnp.zeros((dim,), dtype))

    @staticmethod
    def from_hessian_diagonal(means: jax.Array, hess_diag: jax.Array) -> "Coefficients":
        """var_j ~= 1 / (H_jj + eps) (reference: GLMLossFunction variance path)."""
        return Coefficients(means, 1.0 / (hess_diag + EPSILON))
