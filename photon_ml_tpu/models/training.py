"""Single-model GLM training: regularization sweep with warm start.

reference: ModelTraining.trainGeneralizedLinearModel
(photon-api/.../ModelTraining.scala:35-196): build loss function + optimization
problem, fold over the sorted regularization weights reusing the previous
solution as the next initial point (warm start, line 160-196), optionally
compute coefficient variances.

TPU design: the solve for the whole sweep is ONE compiled program per lambda
value reuse — the regularization weight is a *traced* scalar, so the sweep
runs k solves through a single XLA executable with zero recompilation (the
reference instead mutates optimizer/objective state per lambda).  Training
runs in normalized space and models are mapped back to the original space on
the way out (reference: GeneralizedLinearOptimizationProblem.createModel).
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel, model_for_task
from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
from photon_ml_tpu.ops.features import FeatureMatrix, num_features
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, SolveResult, solve,
)


@dataclasses.dataclass
class TrainedModel:
    """One sweep entry: (lambda, model-in-original-space, tracker).

    reference: ModelTraining returns (lambda -> GLM) plus per-lambda
    ModelTracker (ModelTraining.scala:160-196)."""

    reg_weight: float
    model: GeneralizedLinearModel
    result: SolveResult
    # host-side wall clock of the whole solve (reference: the per-iteration
    # times in OptimizationStatesTracker.scala:32-102 — iterations run inside
    # one XLA program here, so the host can only observe the full solve)
    wall_s: float = 0.0


def train_glm(
    x: FeatureMatrix,
    labels: jax.Array,
    task_type: str,
    *,
    weights: Optional[jax.Array] = None,
    offsets: Optional[jax.Array] = None,
    optimizer_config: OptimizerConfig = OptimizerConfig(),
    regularization: RegularizationContext = RegularizationContext(),
    regularization_weights: Sequence[float] = (0.0,),
    normalization: Optional[NormalizationContext] = None,
    initial_model: Optional[GeneralizedLinearModel] = None,
    warm_start: bool = True,
    compute_variances: bool = False,
    index_map=None,
) -> list[TrainedModel]:
    """Train one GLM per regularization weight, strongest-first with warm
    starts.  Returns models in ORIGINAL feature space.  `index_map`
    resolves named feature constraints (optimizer_config.constraints) into
    positional bounds (reference: GLMSuite.createConstraintFeatureMap)."""
    if optimizer_config.constraints is not None:
        optimizer_config = optimizer_config.resolved_constraints(index_map)
    loss = TASK_LOSSES[task_type]
    d = num_features(x)
    dtype = labels.dtype if jnp.issubdtype(labels.dtype, jnp.floating) else jnp.float32

    objective = GLMObjective(loss, x, labels, weights=weights, offsets=offsets,
                             norm=normalization)

    # x0 is donated (reused in place for the solution): every start point
    # below is a buffer this function owns — fresh zeros, a copy of the
    # caller's initial model, or a copy at the warm-start handoff
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _solve(x0: jax.Array, lam: jax.Array) -> SolveResult:
        return solve(objective, x0, optimizer_config, regularization, lam)

    @jax.jit
    def _hessian_diag(c_original: jax.Array, l2_w: jax.Array) -> jax.Array:
        # variances in original space without normalization, as the reference;
        # the L2 part of the current lambda contributes to the Hessian diagonal
        # (reference: L2Regularization.scala:164-165 adds l2RegWeight)
        return objective.replace(norm=None).with_l2(l2_w).hessian_diagonal(c_original)

    if initial_model is not None:
        x0 = initial_model.coefficients.means.astype(dtype)
        if normalization is not None:
            x0 = normalization.model_to_transformed_space(x0)
        if x0 is initial_model.coefficients.means:
            # same-dtype astype is a no-op: donating would consume the
            # caller's model coefficients
            x0 = jnp.array(x0, copy=True)
    else:
        x0 = jnp.zeros((d,), dtype)

    out: list[TrainedModel] = []
    # strongest regularization first so warm starts move from the most to the
    # least constrained problem (reference: ModelTraining.scala sorted sweep)
    for lam in sorted(regularization_weights, reverse=True):
        t0 = time.perf_counter()
        # without warm start the SAME x0 seeds every lambda: donate a copy
        # so the shared start point survives the sweep
        res = _solve(x0 if warm_start else jnp.array(x0, copy=True),
                     jnp.asarray(lam, dtype))
        float(res.value)  # device->host readback: a true sync even where
        # block_until_ready returns early (tunneled accelerator)
        wall_s = time.perf_counter() - t0
        c_norm = res.x
        c_orig = (normalization.model_to_original_space(c_norm)
                  if normalization is not None else c_norm)
        if compute_variances:
            _, l2_w = regularization.split(jnp.asarray(lam, dtype))
            coeffs = Coefficients.from_hessian_diagonal(
                c_orig, _hessian_diag(c_orig, l2_w))
        else:
            coeffs = Coefficients(c_orig)
        out.append(TrainedModel(float(lam), model_for_task(task_type, coeffs),
                                res, wall_s=wall_s))
        if warm_start:
            # c_norm is res.x, kept alive inside the returned TrainedModel;
            # the next solve donates its x0, so hand it a copy
            x0 = jnp.array(c_norm, copy=True)
    return out


def best_model_by_validation(
    trained: Sequence[TrainedModel],
    evaluate,  # model -> float, higher-is-better decided by caller
) -> TrainedModel:
    """reference: ModelSelection.selectBestLinearRegressionModel etc.
    (photon-client/.../ModelSelection.scala:95) — generic here; the evaluator
    module provides metric direction."""
    scores = [evaluate(t.model) for t in trained]
    return trained[int(max(range(len(scores)), key=lambda i: scores[i]))]
