"""Generalized linear model classes.

reference:
  - GeneralizedLinearModel (photon-api/.../supervised/model/GeneralizedLinearModel.scala:34)
  - LogisticRegressionModel (.../supervised/classification/LogisticRegressionModel.scala:35)
  - SmoothedHingeLossLinearSVMModel (.../classification/SmoothedHingeLossLinearSVMModel.scala)
  - LinearRegressionModel / PoissonRegressionModel (.../supervised/regression/*.scala)

Each model pairs Coefficients with its PointwiseLoss; scoring is a batched
margin (one MXU matvec per shard) and `predict` applies the inverse link
(`loss.mean`).  Classification models expose the BinaryClassifier threshold
API of the reference.
"""
from __future__ import annotations

import dataclasses
from typing import ClassVar, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops import losses as L
from photon_ml_tpu.ops.features import FeatureMatrix


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class GeneralizedLinearModel:
    """Base GLM: margin scoring + mean prediction."""

    coefficients: Coefficients

    loss: ClassVar[L.PointwiseLoss] = L.SQUARED
    task_type: ClassVar[str] = "none"

    def tree_flatten(self):
        return (self.coefficients,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])

    def compute_score(self, x: FeatureMatrix, offsets: Optional[jax.Array] = None) -> jax.Array:
        """Margin z = x.w (+ offset) — reference computeScore."""
        z = self.coefficients.compute_score(x)
        return z if offsets is None else z + offsets

    def predict(self, x: FeatureMatrix, offsets: Optional[jax.Array] = None) -> jax.Array:
        """Mean response — reference computeMean (GeneralizedLinearModel.scala)."""
        return type(self).loss.mean(self.compute_score(x, offsets))

    def validate_coefficients(self) -> bool:
        """reference: GeneralizedLinearModel.validateCoefficients (all finite)."""
        return bool(jnp.all(jnp.isfinite(self.coefficients.means)))

    def with_coefficients(self, coefficients: Coefficients):
        return dataclasses.replace(self, coefficients=coefficients)

    def __len__(self):
        return self.coefficients.dim


class _BinaryClassifier(GeneralizedLinearModel):
    """Threshold API of the reference's BinaryClassifier trait."""

    def predict_class(self, x: FeatureMatrix, offsets: Optional[jax.Array] = None,
                      threshold: float = 0.5) -> jax.Array:
        return (self.predict(x, offsets) >= threshold).astype(jnp.int32)


@jax.tree_util.register_pytree_node_class
class LogisticRegressionModel(_BinaryClassifier):
    loss: ClassVar[L.PointwiseLoss] = L.LOGISTIC
    task_type: ClassVar[str] = "logistic_regression"


@jax.tree_util.register_pytree_node_class
class SmoothedHingeLossLinearSVMModel(_BinaryClassifier):
    loss: ClassVar[L.PointwiseLoss] = L.SMOOTHED_HINGE
    task_type: ClassVar[str] = "smoothed_hinge_loss_linear_svm"

    def predict_class(self, x, offsets=None, threshold: float = 0.0) -> jax.Array:
        # raw-margin classifier: threshold on the margin itself
        return (self.compute_score(x, offsets) >= threshold).astype(jnp.int32)


@jax.tree_util.register_pytree_node_class
class LinearRegressionModel(GeneralizedLinearModel):
    loss: ClassVar[L.PointwiseLoss] = L.SQUARED
    task_type: ClassVar[str] = "linear_regression"


@jax.tree_util.register_pytree_node_class
class PoissonRegressionModel(GeneralizedLinearModel):
    loss: ClassVar[L.PointwiseLoss] = L.POISSON
    task_type: ClassVar[str] = "poisson_regression"


TASK_MODELS = {
    cls.task_type: cls
    for cls in (LogisticRegressionModel, LinearRegressionModel,
                PoissonRegressionModel, SmoothedHingeLossLinearSVMModel)
}


def model_for_task(task_type: str, coefficients: Coefficients) -> GeneralizedLinearModel:
    """Factory, reference: the glmConstructor passed into optimization
    problems (GeneralizedLinearOptimizationProblem.scala:39)."""
    return TASK_MODELS[task_type](coefficients)
