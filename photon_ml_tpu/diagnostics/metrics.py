"""Extended model-quality metrics (the diagnostics metric map).

Rebuild of photon-diagnostics/.../Evaluation.scala:31-198:
  - regression facet: MAE / MSE / RMSE
  - binary facet: area under PR, area under ROC, peak F1
  - per-datum log likelihood (logistic and Poisson families)
  - corrected Akaike information criterion (AICc) from the log likelihood
    and the count of effective (|c| > 1e-9) parameters

The reference computes the binary metrics through spark-mllib
BinaryClassificationMetrics (threshold sweep); here one descending sort
yields the full confusion-count curves.  Host numpy — these are reporting
paths, not training paths.
"""
from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

MEAN_ABSOLUTE_ERROR = "Mean absolute error"
MEAN_SQUARE_ERROR = "Mean square error"
ROOT_MEAN_SQUARE_ERROR = "Root mean square error"
AREA_UNDER_PRECISION_RECALL = "Area under precision/recall"
AREA_UNDER_ROC = "Area under ROC"
PEAK_F1_SCORE = "Peak F1 score"
DATA_LOG_LIKELIHOOD = "Per-datum log likelihood"
AKAIKE_INFORMATION_CRITERION = "Akaike information criterion"
_EPSILON = 1e-9

MetricsMap = Dict[str, float]


def _binary_curves(predictions: np.ndarray, labels: np.ndarray):
    """One descending sort -> (recall, precision, fpr, tpr) step curves with
    threshold at every distinct prediction (the spark-mllib
    BinaryClassificationMetrics sweep, vectorized)."""
    order = np.argsort(-predictions, kind="stable")
    y = labels[order] > 0.5
    p_sorted = predictions[order]
    tp = np.cumsum(y)
    fp = np.cumsum(~y)
    # keep only the last index of each tie-group of predictions
    keep = np.nonzero(np.diff(p_sorted, append=-np.inf))[0]
    tp, fp = tp[keep], fp[keep]
    pos, neg = tp[-1], fp[-1]
    recall = tp / max(pos, 1)
    precision = tp / np.maximum(tp + fp, 1)
    tpr = recall
    fpr = fp / max(neg, 1)
    return recall, precision, fpr, tpr


def _degenerate(labels: np.ndarray) -> bool:
    """Single-class or empty input: threshold metrics are undefined — NaN,
    matching evaluation/evaluators.py (MultiEvaluator then drops the value)."""
    y = labels > 0.5
    return len(labels) == 0 or y.all() or (~y).all()


def area_under_pr(predictions: np.ndarray, labels: np.ndarray) -> float:
    """Trapezoid over the PR curve with the (0, firstPrecision) start point
    spark-mllib prepends (not (0, 1): when the top-scoring tie group contains
    negatives, precision[0] < 1 and starting at 1 would inflate the area)."""
    if _degenerate(labels):
        return float("nan")
    recall, precision, _, _ = _binary_curves(predictions, labels)
    r = np.concatenate([[0.0], recall])
    p = np.concatenate([[precision[0]], precision])
    return float(np.trapezoid(p, r))


def area_under_roc(predictions: np.ndarray, labels: np.ndarray) -> float:
    if _degenerate(labels):
        return float("nan")
    _, _, fpr, tpr = _binary_curves(predictions, labels)
    f = np.concatenate([[0.0], fpr, [1.0]])
    t = np.concatenate([[0.0], tpr, [1.0]])
    return float(np.trapezoid(t, f))


def peak_f1(predictions: np.ndarray, labels: np.ndarray) -> float:
    if _degenerate(labels):
        return float("nan")
    recall, precision, _, _ = _binary_curves(predictions, labels)
    denom = precision + recall
    f1 = np.where(denom > 0, 2 * precision * recall / np.where(denom > 0, denom, 1), 0.0)
    return float(np.max(f1)) if len(f1) else float("nan")


def logistic_log_likelihood(predictions: np.ndarray, labels: np.ndarray) -> float:
    """Mean per-datum log likelihood from predicted probabilities, with the
    reference's epsilon clamping (Evaluation.scala:150-162)."""
    p = np.clip(predictions, _EPSILON, 1.0 - _EPSILON)
    return float(np.mean(labels * np.log(p) + (1.0 - labels) * np.log1p(-p)))


def poisson_log_likelihood(margins: np.ndarray, labels: np.ndarray) -> float:
    """Mean of y*wTx - exp(wTx) - log(y!) (Evaluation.scala:138-148)."""
    from scipy.special import gammaln
    return float(np.mean(labels * margins - np.exp(margins)
                         - gammaln(1.0 + labels)))


def _aicc(log_likelihood_per_datum: float, n: int, coefficients: np.ndarray) -> float:
    """Corrected AIC (Evaluation.scala:105-121): effective parameters =
    coefficients with |c| > 1e-9."""
    k = int(np.sum(np.abs(coefficients) > _EPSILON))
    total_ll = n * log_likelihood_per_datum
    base = 2.0 * (k - total_ll)
    denom = n - k - 1.0
    # JVM double semantics: x/0.0 = Inf (degenerate n <= k+1 case)
    correction = 2.0 * k * (k + 1) / denom if denom != 0 else math.inf
    return base + correction


def evaluate_scores(
    task_type: str,
    predictions: np.ndarray,
    margins: np.ndarray,
    labels: np.ndarray,
    coefficients: Optional[np.ndarray] = None,
) -> MetricsMap:
    """Metric map from precomputed predictions (mean function w/ offset) and
    margins.  Facets by task exactly as the reference matches on model type."""
    predictions = np.asarray(predictions, dtype=np.float64)
    margins = np.asarray(margins, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    m: MetricsMap = {}
    if task_type in ("linear_regression", "poisson_regression"):
        err = predictions - labels
        m[MEAN_ABSOLUTE_ERROR] = float(np.mean(np.abs(err)))
        m[MEAN_SQUARE_ERROR] = float(np.mean(err * err))
        m[ROOT_MEAN_SQUARE_ERROR] = math.sqrt(m[MEAN_SQUARE_ERROR])
    if task_type in ("logistic_regression", "smoothed_hinge_loss_linear_svm"):
        m[AREA_UNDER_PRECISION_RECALL] = area_under_pr(predictions, labels)
        m[AREA_UNDER_ROC] = area_under_roc(predictions, labels)
        m[PEAK_F1_SCORE] = peak_f1(predictions, labels)
    if task_type == "logistic_regression":
        m[DATA_LOG_LIKELIHOOD] = logistic_log_likelihood(predictions, labels)
    elif task_type == "poisson_regression":
        m[DATA_LOG_LIKELIHOOD] = poisson_log_likelihood(margins, labels)
    if DATA_LOG_LIKELIHOOD in m and coefficients is not None:
        m[AKAIKE_INFORMATION_CRITERION] = _aicc(
            m[DATA_LOG_LIKELIHOOD], len(labels), np.asarray(coefficients))
    return m


def evaluate_glm(
    model,
    x,
    labels,
    offsets: Optional[np.ndarray] = None,
    ) -> MetricsMap:
    """reference: Evaluation.evaluate(model, dataSet) — score once with the
    mean function + offset, derive every facet from it."""
    import jax.numpy as jnp
    x = jnp.asarray(np.asarray(x))
    margins = np.asarray(model.compute_score(x), dtype=np.float64)
    if offsets is not None:
        margins = margins + np.asarray(offsets, dtype=np.float64)
    predictions = np.asarray(type(model).loss.mean(jnp.asarray(margins)))
    return evaluate_scores(type(model).task_type, predictions, margins,
                           np.asarray(labels),
                           coefficients=np.asarray(model.coefficients.means))
