"""Hosmer-Lemeshow goodness-of-fit (calibration) test for logistic models.

Rebuild of photon-diagnostics/.../diagnostics/hl/*:
  - bin count heuristic: min(dim + 2, 0.9*sqrt(n) + 0.9*log1p(n))
    (DefaultPredictedProbabilityVersusObservedFrequencyBinner.scala — the
    reference uses DATA_HEURISTIC_FACTOR_A for BOTH terms, reproduced here).
    Deliberate divergence: we floor the count at 3 so chi^2 keeps >= 1 degree
    of freedom on tiny/low-dim inputs; the reference takes the plain min and
    can produce a degenerate (< 3 bin) test there.
  - equal-width predicted-probability bins; per bin chi^2 contribution
    (obs-exp)^2/exp for positives and negatives, skipped when exp == 0, with
    a warning when expected < 5 (HosmerLemeshowDiagnostic.scala:25-120)
  - dof = bins - 2, p-value + standard confidence-level cutoffs
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import numpy as np
from scipy.stats import chi2 as _chi2

STANDARD_CONFIDENCE_LEVELS = (0.000001, 0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
                              0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 0.999999)
MINIMUM_EXPECTED_IN_BUCKET = 5


@dataclasses.dataclass
class HosmerLemeshowBin:
    lower: float
    upper: float
    observed_pos: float
    observed_neg: float
    expected_pos: float
    expected_neg: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class HosmerLemeshowReport:
    chi_squared: float
    degrees_of_freedom: int
    prob_at_chi_square: float          # CDF(chi2) — near 1 = poor calibration
    cutoffs: List[Tuple[float, float]]
    bins: List[HosmerLemeshowBin]
    warnings: List[str]

    @property
    def p_value(self) -> float:
        return 1.0 - self.prob_at_chi_square

    def to_dict(self) -> dict:
        return {"chi_squared": self.chi_squared,
                "degrees_of_freedom": self.degrees_of_freedom,
                "prob_at_chi_square": self.prob_at_chi_square,
                "p_value": self.p_value,
                "cutoffs": self.cutoffs,
                "bins": [b.to_dict() for b in self.bins],
                "warnings": self.warnings}


def _bin_count(num_items: int, num_dimensions: int) -> int:
    by_dim = num_dimensions + 2
    by_data = int(0.9 * math.sqrt(num_items) + 0.9 * math.log1p(num_items))
    return max(3, min(by_data, by_dim))


def hosmer_lemeshow(
    predicted_probabilities,
    labels,
    num_dimensions: int,
) -> HosmerLemeshowReport:
    """reference: HosmerLemeshowDiagnostic.diagnose."""
    p = np.asarray(predicted_probabilities, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64) > 0.5
    n = len(p)
    bins = _bin_count(n, num_dimensions)
    edges = np.linspace(0.0, 1.0, bins + 1)
    which = np.clip(np.digitize(p, edges[1:-1]), 0, bins - 1)

    out_bins: List[HosmerLemeshowBin] = []
    warnings: List[str] = []
    chi2_score = 0.0
    for b in range(bins):
        sel = which == b
        exp_pos = float(p[sel].sum())
        exp_neg = float((1.0 - p[sel]).sum())
        obs_pos = float(y[sel].sum())
        obs_neg = float((~y[sel]).sum())
        if exp_pos > 0:
            chi2_score += (obs_pos - exp_pos) ** 2 / exp_pos
        if exp_neg > 0:
            chi2_score += (obs_neg - exp_neg) ** 2 / exp_neg
        for name, e in (("positive", exp_pos), ("negative", exp_neg)):
            if e < MINIMUM_EXPECTED_IN_BUCKET:
                warnings.append(
                    f"bin [{edges[b]:.3f}, {edges[b + 1]:.3f}): expected "
                    f"{name} count {e:.2f} too small for a sound chi^2 term")
        out_bins.append(HosmerLemeshowBin(float(edges[b]), float(edges[b + 1]),
                                          obs_pos, obs_neg, exp_pos, exp_neg))

    dof = max(1, bins - 2)
    dist = _chi2(dof)
    cutoffs = [(lvl, float(dist.ppf(lvl))) for lvl in STANDARD_CONFIDENCE_LEVELS]
    return HosmerLemeshowReport(
        chi_squared=float(chi2_score), degrees_of_freedom=dof,
        prob_at_chi_square=float(dist.cdf(chi2_score)),
        cutoffs=cutoffs, bins=out_bins, warnings=warnings)
