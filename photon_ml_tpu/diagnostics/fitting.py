"""Fitting diagnostic: learning curves over growing training portions.

Rebuild of photon-diagnostics/.../fitting/FittingDiagnostic.scala:33-131:
tag rows into 10 partitions, hold partition 9 out, train on growing prefixes
(1/9, 2/9, ... of the non-holdout data) with warm starts, record each metric
on train and holdout per portion.  Subsets are weight masks over the shared
feature matrix — no data movement.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.metrics import MetricsMap, evaluate_scores
from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, solve,
)

NUM_TRAINING_PARTITIONS = 10          # reference: FittingDiagnostic object
MIN_SAMPLES_PER_PARTITION_PER_DIMENSION = 10


@dataclasses.dataclass
class FittingReport:
    # metric -> {"portions": [...], "train": [...], "test": [...]}
    metrics: Dict[str, Dict[str, List[float]]]
    message: str = ""

    def to_dict(self) -> dict:
        return {"metrics": self.metrics, "message": self.message}


def fitting_diagnostic(
    x,
    labels,
    task_type: str,
    *,
    weights: Optional[np.ndarray] = None,
    offsets: Optional[np.ndarray] = None,
    optimizer_config: OptimizerConfig = OptimizerConfig(),
    regularization: RegularizationContext = RegularizationContext(),
    regularization_weight: float = 0.0,
    seed: int = 7,
) -> FittingReport:
    """reference: FittingDiagnostic.diagnose.  Returns an empty report when
    there is not enough data (reference: numSamples <= dim * 10 guard)."""
    x = jnp.asarray(np.asarray(x))
    n, d = x.shape
    if n <= d * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION:
        return FittingReport({}, message=(
            f"not enough data for learning curves: {n} rows <= "
            f"{d * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION}"))

    y = jnp.asarray(np.asarray(labels, dtype=np.float64), x.dtype)
    base_w = (np.ones(n) if weights is None
              else np.asarray(weights, dtype=np.float64))
    rng = np.random.default_rng(seed)
    tags = rng.integers(0, NUM_TRAINING_PARTITIONS, size=n)
    holdout = tags == NUM_TRAINING_PARTITIONS - 1
    off = None if offsets is None else jnp.asarray(np.asarray(offsets), x.dtype)
    loss = TASK_LOSSES[task_type]
    labels_np = np.asarray(labels, dtype=np.float64)

    curves: Dict[str, Dict[str, List[float]]] = {}
    x0 = jnp.zeros((d,), x.dtype)
    lam = jnp.asarray(regularization_weight, x.dtype)
    for max_tag in range(NUM_TRAINING_PARTITIONS - 1):
        member = tags <= max_tag
        portion = 100.0 * member.sum() / n
        w = jnp.asarray(member * base_w, x.dtype)
        obj = GLMObjective(loss, x, y, weights=w, offsets=off)
        res = solve(obj, x0, optimizer_config, regularization, lam)
        x0 = res.x  # warm start the next, larger portion (reference scanLeft)
        margins = np.asarray(x @ res.x)
        if offsets is not None:
            margins = margins + np.asarray(offsets)
        preds = np.asarray(loss.mean(jnp.asarray(margins)))
        coefs = np.asarray(res.x)
        m_train = evaluate_scores(task_type, preds[member], margins[member],
                                  labels_np[member], coefficients=coefs)
        m_test = evaluate_scores(task_type, preds[holdout], margins[holdout],
                                 labels_np[holdout], coefficients=coefs)
        for metric, v_test in m_test.items():
            entry = curves.setdefault(
                metric, {"portions": [], "train": [], "test": []})
            entry["portions"].append(round(portion, 2))
            entry["train"].append(m_train.get(metric, float("nan")))
            entry["test"].append(v_test)
    return FittingReport(curves)
