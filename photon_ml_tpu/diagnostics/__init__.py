from photon_ml_tpu.diagnostics.metrics import evaluate_glm, evaluate_scores  # noqa: F401
from photon_ml_tpu.diagnostics.bootstrap import (  # noqa: F401
    BootstrapReport, CoefficientSummary, bootstrap_training,
)
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport, hosmer_lemeshow  # noqa: F401
from photon_ml_tpu.diagnostics.independence import KendallTauReport, kendall_tau_analysis  # noqa: F401
from photon_ml_tpu.diagnostics.importance import FeatureImportanceReport, feature_importance  # noqa: F401
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic  # noqa: F401
from photon_ml_tpu.diagnostics.report import (DiagnosticReport,  # noqa: F401
                                              render_html, render_markdown)
