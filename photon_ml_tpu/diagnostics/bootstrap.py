"""Bootstrap training: coefficient and metric confidence intervals.

Rebuild of photon-diagnostics/.../BootstrapTraining.scala:29-181 +
CoefficientSummary.scala + BootstrapTrainingDiagnostic.scala.

The reference tags every row with one of 1000 random splits and, per
bootstrap replica, filters the RDD into train/holdout subsets and runs a full
Spark training job (strategy P7, SURVEY §2.14).  TPU design: a replica IS a
weight vector.  Row membership for all k replicas is drawn as a [k, n] 0/1
matrix, training weights = w * member, holdout weights = w * (1-member), and
ALL k solves run as ONE vmapped XLA program over the replica axis — no data
movement, no per-replica jobs.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.metrics import MetricsMap, evaluate_scores
from photon_ml_tpu.ops import TASK_LOSSES, GLMObjective
from photon_ml_tpu.optim import (
    OptimizerConfig, RegularizationContext, solve,
)


@dataclasses.dataclass
class CoefficientSummary:
    """Five-number summary + mean/std over bootstrap replicas (reference:
    supervised/model/CoefficientSummary.scala — quartiles/min/max)."""

    min: float
    q1: float
    median: float
    q3: float
    max: float
    mean: float
    std: float

    @staticmethod
    def from_samples(samples: np.ndarray) -> "CoefficientSummary":
        s = np.asarray(samples, dtype=np.float64)
        q1, med, q3 = np.percentile(s, [25, 50, 75])
        return CoefficientSummary(float(s.min()), float(q1), float(med),
                                  float(q3), float(s.max()),
                                  float(s.mean()), float(s.std()))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class BootstrapReport:
    num_samples: int
    # per-coefficient summaries, 1:1 with the coefficient vector
    coefficient_summaries: List[CoefficientSummary]
    # metric name -> summary over replicas (holdout evaluation)
    metric_summaries: Dict[str, CoefficientSummary]
    # per-coefficient: True when the cross-replica IQR excludes zero
    significant_mask: np.ndarray

    def to_dict(self) -> dict:
        return {
            "num_samples": self.num_samples,
            "coefficient_summaries": [c.to_dict() for c in self.coefficient_summaries],
            "metric_summaries": {k: v.to_dict() for k, v in self.metric_summaries.items()},
            "num_significant": int(self.significant_mask.sum()),
        }


@functools.lru_cache(maxsize=32)
def _replica_solver(loss, config: OptimizerConfig, reg: RegularizationContext):
    # only the per-replica weight row varies; data/offsets are shared
    def solve_one(x, labels, weights, offsets, x0, lam):
        obj = GLMObjective(loss, x, labels, weights=weights, offsets=offsets)
        return solve(obj, x0, config, reg, lam)
    return jax.jit(jax.vmap(solve_one, in_axes=(None, None, 0, None, None, None)))


def bootstrap_training(
    x,
    labels,
    task_type: str,
    *,
    num_bootstrap_samples: int = 10,
    training_portion: float = 0.75,
    weights: Optional[np.ndarray] = None,
    offsets: Optional[np.ndarray] = None,
    optimizer_config: OptimizerConfig = OptimizerConfig(),
    regularization: RegularizationContext = RegularizationContext(),
    regularization_weight: float = 0.0,
    warm_start: Optional[np.ndarray] = None,
    seed: int = 7,
) -> BootstrapReport:
    """Train k replica models on random subsamples, evaluate each on its
    holdout, aggregate coefficient + metric CIs.

    reference: BootstrapTraining.bootstrap (scala:132-181; split-tag
    subsampling with the `populationPortionPerBootstrapSample` cap at 0.9)
    plus aggregateCoefficient/MetricsConfidenceIntervals (scala:48-100).
    """
    if num_bootstrap_samples <= 1:
        raise ValueError("number of bootstrap samples must be > 1")
    if not 0.0 < training_portion <= 1.0:
        raise ValueError("training portion must be in (0, 1]")
    portion = min(0.9, training_portion)  # reference: never more than 90%

    x = jnp.asarray(np.asarray(x))
    y = jnp.asarray(np.asarray(labels, dtype=x.dtype))
    n, d = x.shape
    base_w = (np.ones(n) if weights is None
              else np.asarray(weights, dtype=np.float64))
    rng = np.random.default_rng(seed)
    member = (rng.random((num_bootstrap_samples, n)) < portion)
    train_w = jnp.asarray(member * base_w, x.dtype)
    x0 = (jnp.zeros((d,), x.dtype) if warm_start is None
          else jnp.asarray(warm_start, x.dtype))

    loss = TASK_LOSSES[task_type]
    off = None if offsets is None else jnp.asarray(np.asarray(offsets), x.dtype)
    solver = _replica_solver(loss, optimizer_config, regularization)
    res = solver(x, y, train_w, off, x0, jnp.asarray(regularization_weight, x.dtype))
    coefs = np.asarray(res.x)                       # [k, d]

    # holdout metrics per replica (host-side reporting loop)
    margins_all = np.asarray(x @ res.x.T).T         # [k, n]
    if offsets is not None:
        margins_all = margins_all + np.asarray(offsets)
    per_metric: Dict[str, List[float]] = {}
    labels_np = np.asarray(labels, dtype=np.float64)
    for r in range(num_bootstrap_samples):
        hold = ~member[r]
        if not hold.any():
            continue
        margins = margins_all[r, hold]
        preds = np.asarray(loss.mean(jnp.asarray(margins)))
        metrics = evaluate_scores(task_type, preds, margins, labels_np[hold],
                                  coefficients=coefs[r])
        for k_, v in metrics.items():
            per_metric.setdefault(k_, []).append(v)

    coef_summaries = [CoefficientSummary.from_samples(coefs[:, j])
                      for j in range(d)]
    metric_summaries = {k_: CoefficientSummary.from_samples(np.asarray(v))
                        for k_, v in per_metric.items()}
    significant = np.asarray([(c.q1 > 0) or (c.q3 < 0) for c in coef_summaries])
    return BootstrapReport(
        num_samples=num_bootstrap_samples,
        coefficient_summaries=coef_summaries,
        metric_summaries=metric_summaries,
        significant_mask=significant)
