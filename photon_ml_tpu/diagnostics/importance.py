"""Feature importance rankings.

Rebuild of photon-diagnostics/.../featureimportance/*:
  - expected-magnitude importance |c_j * meanAbs(x_j)|
    (ExpectedMagnitudeFeatureImportanceDiagnostic.scala:42-58)
  - variance importance |c_j * var(x_j)|
    (VarianceFeatureImportanceDiagnostic.scala:41-57)
ranked descending with the rank -> importance summary the HTML report plots.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.stats import BasicStatisticalSummary


@dataclasses.dataclass
class FeatureImportanceReport:
    importance_type: str
    # (feature key, index, importance), sorted descending by importance
    ranked: List[Tuple[str, int, float]]

    def top(self, k: int = 20) -> List[Tuple[str, int, float]]:
        return self.ranked[:k]

    def to_dict(self, top_k: int = 50) -> dict:
        return {"importance_type": self.importance_type,
                "top": [{"feature": f, "index": i, "importance": v}
                        for f, i, v in self.top(top_k)]}


def feature_importance(
    coefficients,
    summary: Optional[BasicStatisticalSummary] = None,
    feature_keys: Optional[Sequence[str]] = None,
    importance_type: str = "expected_magnitude",
) -> FeatureImportanceReport:
    """importance_type in {"expected_magnitude", "variance"}; without a
    statistics summary every feature scale defaults to 1 (reference: the
    summary None case in getImportances)."""
    c = np.asarray(coefficients, dtype=np.float64)
    if importance_type == "expected_magnitude":
        scale = summary.mean_abs if summary is not None else np.ones_like(c)
    elif importance_type == "variance":
        scale = summary.variance if summary is not None else np.ones_like(c)
    else:
        raise ValueError(f"unknown importance type {importance_type!r}")
    imp = np.abs(c * np.asarray(scale))
    keys = (list(feature_keys) if feature_keys is not None
            else [f"feature_{j}" for j in range(len(c))])
    order = np.argsort(-imp, kind="stable")
    ranked = [(keys[j], int(j), float(imp[j])) for j in order]
    return FeatureImportanceReport(importance_type, ranked)
