"""Kendall-tau independence analysis between two paired samples.

Rebuild of photon-diagnostics/.../independence/KendallTauAnalysis.scala:35-131:
concordant/discordant/tied pair counts -> tau-alpha, tau-beta, z score, and a
two-sided normal probability.  The reference samples down to ~sqrt(n) points
then forms the full Cartesian pair set through a Spark shuffle; here the
subsample's pair comparison is one numpy broadcast.

Used to test whether prediction errors are independent of the predictions
(the legacy driver pairs (prediction, error)).
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np


@dataclasses.dataclass
class KendallTauReport:
    num_concordant: int
    num_discordant: int
    num_items: int
    num_pairs: int
    effective_pairs: int
    tau_alpha: float
    tau_beta: float
    z_alpha: float
    p_value: float          # two-sided mass inside |z| (reference convention)
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def kendall_tau_analysis(a, b, max_items: int = 2000, seed: int = 7
                         ) -> KendallTauReport:
    """reference: KendallTauAnalysis.analyze (pair classification at
    checkConcordance, scala:104-131; statistics at scala:64-90)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if len(a) != len(b):
        raise ValueError("paired samples must have equal length")
    n_all = len(a)
    # reference rate = min(1, sqrt(n)/n) -> expected sample ~sqrt(n); a floor
    # of 200 is added (deliberate divergence) so small inputs keep enough
    # pairs for a meaningful z-score, and max_items caps the O(m^2) compare
    target = min(n_all, max_items, max(200, int(math.sqrt(n_all))))
    if n_all > target:
        idx = np.random.default_rng(seed).choice(n_all, size=target, replace=False)
        a, b = a[idx], b[idx]
    m = len(a)

    da = np.sign(a[:, None] - a[None, :])
    db = np.sign(b[:, None] - b[None, :])
    iu = np.triu_indices(m, k=1)
    da, db = da[iu], db[iu]
    concordant = int(np.sum((da != 0) & (da == db)))
    discordant = int(np.sum((da != 0) & (db != 0) & (da != db)))
    ties_a = int(np.sum(da == 0))
    ties_b = int(np.sum((da != 0) & (db == 0)))

    num_pairs = m * (m - 1) // 2
    no_ties_a = num_pairs - ties_a
    no_ties_b = num_pairs - ties_b
    cd = concordant + discordant
    tau_alpha = (concordant - discordant) / cd if cd else 0.0
    denom = math.sqrt(float(no_ties_a) * float(no_ties_b))
    tau_beta = (concordant - discordant) / denom if denom else 0.0
    var_num = 2.0 * (2.0 * m + 5.0)
    var_den = 9.0 * m * (m - 1)
    d = math.sqrt(var_num / var_den) if var_den > 0 else 1.0
    z_alpha = tau_alpha / d
    p_value = math.erf(abs(z_alpha) / math.sqrt(2.0))

    msg = ""
    if ties_a + ties_b > 0:
        msg = (f"detected ties (A: {ties_a}, B: {ties_b}); the tau-alpha "
               "z-score over-estimates independence")
    return KendallTauReport(concordant, discordant, m, num_pairs, cd,
                            tau_alpha, tau_beta, z_alpha, p_value, msg)
