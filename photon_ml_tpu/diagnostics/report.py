"""Diagnostic report assembly + JSON/markdown rendering.

Replaces the reference's HTML reporting framework (photon-diagnostics/
.../diagnostics/reporting/ — LogicalReport -> PhysicalReport -> xchart/batik
HTML, ~1500 LoC).  Per SURVEY §7 ("What NOT to port"), rendering is JSON +
markdown: the ANALYSES carry the value, the presentation layer does not.
Assembled per the legacy driver's diagnose stage (Driver.scala:468-607):
metrics + Hosmer-Lemeshow + bootstrap + feature importance + fitting curves
+ prediction-error independence.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.importance import FeatureImportanceReport
from photon_ml_tpu.diagnostics.independence import KendallTauReport


@dataclasses.dataclass
class DiagnosticReport:
    task_type: str
    metrics: Dict[str, float]
    feature_importance: Optional[FeatureImportanceReport] = None
    hosmer_lemeshow: Optional[HosmerLemeshowReport] = None
    independence: Optional[KendallTauReport] = None
    bootstrap: Optional[BootstrapReport] = None
    fitting: Optional[FittingReport] = None

    def to_dict(self) -> dict:
        d = {"task_type": self.task_type, "metrics": self.metrics}
        if self.feature_importance is not None:
            d["feature_importance"] = self.feature_importance.to_dict()
        if self.hosmer_lemeshow is not None:
            d["hosmer_lemeshow"] = self.hosmer_lemeshow.to_dict()
        if self.independence is not None:
            d["independence"] = self.independence.to_dict()
        if self.bootstrap is not None:
            d["bootstrap"] = self.bootstrap.to_dict()
        if self.fitting is not None:
            d["fitting"] = self.fitting.to_dict()
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)


def render_markdown(report: DiagnosticReport) -> str:
    """Markdown rendering of the full report (the reference renders chapters/
    sections/plots to HTML; same structure, portable format)."""
    lines: List[str] = [f"# Model diagnostic report ({report.task_type})", ""]

    lines += ["## Metrics", "", "| metric | value |", "|---|---|"]
    for k, v in sorted(report.metrics.items()):
        lines.append(f"| {k} | {v:.6g} |")
    lines.append("")

    if report.feature_importance is not None:
        fi = report.feature_importance
        lines += [f"## Feature importance ({fi.importance_type})", "",
                  "| rank | feature | importance |", "|---|---|---|"]
        for rank, (feat, _idx, imp) in enumerate(fi.top(20), 1):
            lines.append(f"| {rank} | {feat} | {imp:.6g} |")
        lines.append("")

    if report.hosmer_lemeshow is not None:
        hl = report.hosmer_lemeshow
        lines += ["## Hosmer-Lemeshow calibration", "",
                  f"- chi-squared: {hl.chi_squared:.4f} "
                  f"({hl.degrees_of_freedom} dof)",
                  f"- P(chi2 <= observed): {hl.prob_at_chi_square:.4f} "
                  f"(p-value {hl.p_value:.4f})", ""]
        lines += ["| bin | expected + | observed + | expected - | observed - |",
                  "|---|---|---|---|---|"]
        for b in hl.bins:
            lines.append(f"| [{b.lower:.2f}, {b.upper:.2f}) | "
                         f"{b.expected_pos:.1f} | {b.observed_pos:.0f} | "
                         f"{b.expected_neg:.1f} | {b.observed_neg:.0f} |")
        if hl.warnings:
            lines += ["", f"warnings: {len(hl.warnings)} sparse bins"]
        lines.append("")

    if report.independence is not None:
        kt = report.independence
        lines += ["## Prediction-error independence (Kendall tau)", "",
                  f"- tau-alpha: {kt.tau_alpha:.4f}, tau-beta: {kt.tau_beta:.4f}",
                  f"- z: {kt.z_alpha:.3f}, two-sided probability: {kt.p_value:.4f}"]
        if kt.message:
            lines.append(f"- note: {kt.message}")
        lines.append("")

    if report.bootstrap is not None:
        bs = report.bootstrap
        lines += ["## Bootstrap confidence intervals", "",
                  f"- replicas: {bs.num_samples}",
                  f"- coefficients with IQR excluding zero: "
                  f"{int(bs.significant_mask.sum())} / "
                  f"{len(bs.coefficient_summaries)}", "",
                  "| metric | q1 | median | q3 |", "|---|---|---|---|"]
        for k, s in sorted(bs.metric_summaries.items()):
            lines.append(f"| {k} | {s.q1:.6g} | {s.median:.6g} | {s.q3:.6g} |")
        lines.append("")

    if report.fitting is not None and report.fitting.metrics:
        lines += ["## Learning curves", ""]
        for metric, curve in sorted(report.fitting.metrics.items()):
            lines += [f"### {metric}", "",
                      "| train % | train | holdout |", "|---|---|---|"]
            for p, tr, te in zip(curve["portions"], curve["train"],
                                 curve["test"]):
                lines.append(f"| {p:.1f} | {tr:.6g} | {te:.6g} |")
            lines.append("")
    elif report.fitting is not None:
        lines += ["## Learning curves", "", report.fitting.message, ""]

    return "\n".join(lines)
