"""Diagnostic report assembly + JSON/markdown/HTML rendering.

Replaces the reference's HTML reporting framework (photon-diagnostics/
.../diagnostics/reporting/ — LogicalReport -> PhysicalReport -> xchart/batik
HTML, ~1500 LoC).  The ANALYSES carry the value; rendering is JSON +
markdown + one SELF-CONTAINED html file (inline CSS + inline SVG charts,
no plotting stack, closing VERDICT r4 coverage item #95).  Assembled per
the legacy driver's diagnose stage (Driver.scala:468-607): metrics +
Hosmer-Lemeshow + bootstrap + feature importance + fitting curves +
prediction-error independence.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.importance import FeatureImportanceReport
from photon_ml_tpu.diagnostics.independence import KendallTauReport


@dataclasses.dataclass
class DiagnosticReport:
    task_type: str
    metrics: Dict[str, float]
    feature_importance: Optional[FeatureImportanceReport] = None
    hosmer_lemeshow: Optional[HosmerLemeshowReport] = None
    independence: Optional[KendallTauReport] = None
    bootstrap: Optional[BootstrapReport] = None
    fitting: Optional[FittingReport] = None

    def to_dict(self) -> dict:
        d = {"task_type": self.task_type, "metrics": self.metrics}
        if self.feature_importance is not None:
            d["feature_importance"] = self.feature_importance.to_dict()
        if self.hosmer_lemeshow is not None:
            d["hosmer_lemeshow"] = self.hosmer_lemeshow.to_dict()
        if self.independence is not None:
            d["independence"] = self.independence.to_dict()
        if self.bootstrap is not None:
            d["bootstrap"] = self.bootstrap.to_dict()
        if self.fitting is not None:
            d["fitting"] = self.fitting.to_dict()
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)


def render_markdown(report: DiagnosticReport) -> str:
    """Markdown rendering of the full report (the reference renders chapters/
    sections/plots to HTML; same structure, portable format)."""
    lines: List[str] = [f"# Model diagnostic report ({report.task_type})", ""]

    lines += ["## Metrics", "", "| metric | value |", "|---|---|"]
    for k, v in sorted(report.metrics.items()):
        lines.append(f"| {k} | {v:.6g} |")
    lines.append("")

    if report.feature_importance is not None:
        fi = report.feature_importance
        lines += [f"## Feature importance ({fi.importance_type})", "",
                  "| rank | feature | importance |", "|---|---|---|"]
        for rank, (feat, _idx, imp) in enumerate(fi.top(20), 1):
            lines.append(f"| {rank} | {feat} | {imp:.6g} |")
        lines.append("")

    if report.hosmer_lemeshow is not None:
        hl = report.hosmer_lemeshow
        lines += ["## Hosmer-Lemeshow calibration", "",
                  f"- chi-squared: {hl.chi_squared:.4f} "
                  f"({hl.degrees_of_freedom} dof)",
                  f"- P(chi2 <= observed): {hl.prob_at_chi_square:.4f} "
                  f"(p-value {hl.p_value:.4f})", ""]
        lines += ["| bin | expected + | observed + | expected - | observed - |",
                  "|---|---|---|---|---|"]
        for b in hl.bins:
            lines.append(f"| [{b.lower:.2f}, {b.upper:.2f}) | "
                         f"{b.expected_pos:.1f} | {b.observed_pos:.0f} | "
                         f"{b.expected_neg:.1f} | {b.observed_neg:.0f} |")
        if hl.warnings:
            lines += ["", f"warnings: {len(hl.warnings)} sparse bins"]
        lines.append("")

    if report.independence is not None:
        kt = report.independence
        lines += ["## Prediction-error independence (Kendall tau)", "",
                  f"- tau-alpha: {kt.tau_alpha:.4f}, tau-beta: {kt.tau_beta:.4f}",
                  f"- z: {kt.z_alpha:.3f}, two-sided probability: {kt.p_value:.4f}"]
        if kt.message:
            lines.append(f"- note: {kt.message}")
        lines.append("")

    if report.bootstrap is not None:
        bs = report.bootstrap
        lines += ["## Bootstrap confidence intervals", "",
                  f"- replicas: {bs.num_samples}",
                  f"- coefficients with IQR excluding zero: "
                  f"{int(bs.significant_mask.sum())} / "
                  f"{len(bs.coefficient_summaries)}", "",
                  "| metric | q1 | median | q3 |", "|---|---|---|---|"]
        for k, s in sorted(bs.metric_summaries.items()):
            lines.append(f"| {k} | {s.q1:.6g} | {s.median:.6g} | {s.q3:.6g} |")
        lines.append("")

    if report.fitting is not None and report.fitting.metrics:
        lines += ["## Learning curves", ""]
        for metric, curve in sorted(report.fitting.metrics.items()):
            lines += [f"### {metric}", "",
                      "| train % | train | holdout |", "|---|---|---|"]
            for p, tr, te in zip(curve["portions"], curve["train"],
                                 curve["test"]):
                lines.append(f"| {p:.1f} | {tr:.6g} | {te:.6g} |")
            lines.append("")
    elif report.fitting is not None:
        lines += ["## Learning curves", "", report.fitting.message, ""]

    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self-contained HTML rendering (inline CSS + inline SVG, no plotting stack)
# ---------------------------------------------------------------------------

# categorical slots 1-2 of the skill-validated default palette (CVD-checked),
# stepped separately for light and dark surfaces; text wears ink tokens only
_CSS = """
:root { color-scheme: light dark;
  --surface: #ffffff; --ink: #1a1a19; --ink-2: #5f5e56; --grid: #e4e3dd;
  --s1: #2a78d6; --s2: #eb6834; }
@media (prefers-color-scheme: dark) { :root {
  --surface: #1a1a19; --ink: #ffffff; --ink-2: #c3c2b7; --grid: #3a3936;
  --s1: #3987e5; --s2: #d95926; } }
body { background: var(--surface); color: var(--ink); margin: 2rem auto;
  max-width: 60rem; padding: 0 1rem;
  font: 14px/1.5 system-ui, -apple-system, sans-serif; }
h1 { font-size: 1.4rem; } h2 { font-size: 1.1rem; margin-top: 2rem; }
h3 { font-size: 0.95rem; color: var(--ink-2); }
table { border-collapse: collapse; margin: 0.5rem 0; }
th, td { text-align: left; padding: 0.25rem 0.9rem 0.25rem 0;
  border-bottom: 1px solid var(--grid); font-variant-numeric: tabular-nums; }
th { color: var(--ink-2); font-weight: 600; }
.note { color: var(--ink-2); }
svg text { fill: var(--ink-2); font: 11px system-ui, sans-serif; }
svg .lbl { fill: var(--ink); }
svg line.grid { stroke: var(--grid); stroke-width: 1; }
.legend span { margin-right: 1.2rem; }
.legend i { display: inline-block; width: 10px; height: 10px;
  border-radius: 2px; margin-right: 0.35rem; }
"""


def _esc(s) -> str:
    import html
    return html.escape(str(s))


def _table(headers, rows) -> str:
    h = "".join(f"<th>{_esc(c)}</th>" for c in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><thead><tr>{h}</tr></thead><tbody>{body}</tbody></table>"


def _legend(entries) -> str:
    return "<div class='legend'>" + "".join(
        f"<span><i style='background:var({var})'></i>{_esc(lbl)}</span>"
        for var, lbl in entries) + "</div>"


def _svg_lines(x, series, x_label, w=560, h=240):
    """Line chart: `series` = [(css-var, label, ys)]; 2px lines, >=8px
    markers with native <title> tooltips, end-of-line direct labels."""
    pad_l, pad_r, pad_t, pad_b = 42, 70, 8, 26
    ys_all = [v for _, _, ys in series for v in ys
              if v == v and abs(v) != float("inf")]
    if not ys_all or len(x) < 2:
        return ""
    lo, hi = min(ys_all), max(ys_all)
    if hi == lo:
        hi = lo + (abs(lo) or 1.0)
    span_x = max(x) - min(x) or 1.0
    sx = lambda v: pad_l + (v - min(x)) / span_x * (w - pad_l - pad_r)
    sy = lambda v: pad_t + (hi - v) / (hi - lo) * (h - pad_t - pad_b)
    out = [f"<svg viewBox='0 0 {w} {h}' role='img' "
           f"style='max-width:{w}px'>"]
    for frac in (0.0, 0.5, 1.0):
        gy = pad_t + frac * (h - pad_t - pad_b)
        gv = hi - frac * (hi - lo)
        out.append(f"<line class='grid' x1='{pad_l}' x2='{w - pad_r}' "
                   f"y1='{gy:.1f}' y2='{gy:.1f}'/>")
        out.append(f"<text x='{pad_l - 6}' y='{gy + 4:.1f}' "
                   f"text-anchor='end'>{gv:.3g}</text>")
    finite = lambda v: v == v and abs(v) != float("inf")
    for var, label, ys in series:
        # NaN points (single-class holdout AUC, missing train-side metric)
        # are dropped from the marks, not written as 'nan' coordinates that
        # would make browsers discard the whole polyline
        pairs = [(a, b) for a, b in zip(x, ys) if finite(b)]
        if not pairs:
            continue
        pts = " ".join(f"{sx(a):.1f},{sy(b):.1f}" for a, b in pairs)
        out.append(f"<polyline points='{pts}' fill='none' "
                   f"stroke='var({var})' stroke-width='2'/>")
        for a, b in pairs:
            out.append(
                f"<circle cx='{sx(a):.1f}' cy='{sy(b):.1f}' r='4' "
                f"fill='var({var})' stroke='var(--surface)' "
                f"stroke-width='2'><title>{_esc(label)} @ {a:g}: "
                f"{b:.6g}</title></circle>")
        out.append(f"<text class='lbl' x='{w - pad_r + 8}' "
                   f"y='{sy(pairs[-1][1]) + 4:.1f}'>{_esc(label)}</text>")
    out.append(f"<text x='{(pad_l + w - pad_r) / 2:.0f}' y='{h - 6}' "
               f"text-anchor='middle'>{_esc(x_label)}</text>")
    out.append("</svg>")
    return "".join(out)


def _svg_grouped_bars(groups, series, w=560, h=240):
    """Grouped bars: `groups` = x labels, `series` = [(css-var, label,
    values)]; 2px gap between bars, native <title> tooltips."""
    pad_l, pad_t, pad_b = 42, 8, 26
    vals = [v for _, _, vs in series for v in vs]
    hi = max(vals + [0.0]) or 1.0
    n, k = len(groups), len(series)
    slot = (w - pad_l) / max(n, 1)
    bar_w = max((slot - 8) / max(k, 1) - 2, 2)
    sy = lambda v: pad_t + (hi - v) / hi * (h - pad_t - pad_b)
    out = [f"<svg viewBox='0 0 {w} {h}' role='img' "
           f"style='max-width:{w}px'>"]
    for frac in (0.0, 0.5):
        gy = pad_t + frac * (h - pad_t - pad_b)
        out.append(f"<line class='grid' x1='{pad_l}' x2='{w}' "
                   f"y1='{gy:.1f}' y2='{gy:.1f}'/>")
        out.append(f"<text x='{pad_l - 6}' y='{gy + 4:.1f}' "
                   f"text-anchor='end'>{hi * (1 - frac):.3g}</text>")
    base = sy(0.0)
    out.append(f"<line class='grid' x1='{pad_l}' x2='{w}' y1='{base:.1f}' "
               f"y2='{base:.1f}'/>")
    for g, gname in enumerate(groups):
        x0 = pad_l + g * slot + 4
        for s, (var, label, vs) in enumerate(series):
            v = vs[g]
            top = sy(v)
            out.append(
                f"<rect x='{x0 + s * (bar_w + 2):.1f}' y='{top:.1f}' "
                f"width='{bar_w:.1f}' height='{max(base - top, 0):.1f}' "
                f"rx='2' fill='var({var})'><title>{_esc(label)} "
                f"{_esc(gname)}: {v:.6g}</title></rect>")
        if n <= 12:
            out.append(f"<text x='{x0 + (slot - 8) / 2:.1f}' y='{h - 6}' "
                       f"text-anchor='middle'>{_esc(gname)}</text>")
    out.append("</svg>")
    return "".join(out)


def render_html(report: DiagnosticReport) -> str:
    """One self-contained HTML file: the markdown report's content with
    inline-SVG charts for calibration and learning curves (the reference
    renders these through xchart/batik; same content, zero dependencies)."""
    parts = [f"<!doctype html><html lang='en'><head><meta charset='utf-8'>",
             f"<title>Model diagnostic report ({_esc(report.task_type)})"
             f"</title><style>{_CSS}</style></head><body>",
             f"<h1>Model diagnostic report ({_esc(report.task_type)})</h1>"]

    parts.append("<h2>Metrics</h2>")
    parts.append(_table(["metric", "value"],
                        [(k, f"{v:.6g}") for k, v in
                         sorted(report.metrics.items())]))

    if report.feature_importance is not None:
        fi = report.feature_importance
        parts.append(f"<h2>Feature importance ({_esc(fi.importance_type)})"
                     "</h2>")
        parts.append(_table(
            ["rank", "feature", "importance"],
            [(r, feat, f"{imp:.6g}") for r, (feat, _i, imp)
             in enumerate(fi.top(20), 1)]))

    if report.hosmer_lemeshow is not None:
        hl = report.hosmer_lemeshow
        parts.append("<h2>Hosmer-Lemeshow calibration</h2>")
        parts.append(
            f"<p>chi-squared {hl.chi_squared:.4f} "
            f"({hl.degrees_of_freedom} dof), "
            f"P(chi2 &le; observed) {hl.prob_at_chi_square:.4f}, "
            f"p-value {hl.p_value:.4f}</p>")
        groups = [f"[{b.lower:.2f},{b.upper:.2f})" for b in hl.bins]
        series = [("--s1", "expected +", [b.expected_pos for b in hl.bins]),
                  ("--s2", "observed +", [b.observed_pos for b in hl.bins])]
        parts.append(_legend([("--s1", "expected positives"),
                              ("--s2", "observed positives")]))
        parts.append(_svg_grouped_bars(groups, series))
        parts.append(_table(
            ["bin", "expected +", "observed +", "expected -", "observed -"],
            [(f"[{b.lower:.2f}, {b.upper:.2f})", f"{b.expected_pos:.1f}",
              f"{b.observed_pos:.0f}", f"{b.expected_neg:.1f}",
              f"{b.observed_neg:.0f}") for b in hl.bins]))
        if hl.warnings:
            parts.append(f"<p class='note'>warnings: {len(hl.warnings)} "
                         "sparse bins</p>")

    if report.independence is not None:
        kt = report.independence
        parts.append("<h2>Prediction-error independence (Kendall tau)</h2>")
        parts.append(f"<p>tau-alpha {kt.tau_alpha:.4f}, "
                     f"tau-beta {kt.tau_beta:.4f}, z {kt.z_alpha:.3f}, "
                     f"two-sided probability {kt.p_value:.4f}</p>")
        if kt.message:
            parts.append(f"<p class='note'>{_esc(kt.message)}</p>")

    if report.bootstrap is not None:
        bs = report.bootstrap
        parts.append("<h2>Bootstrap confidence intervals</h2>")
        parts.append(
            f"<p>{bs.num_samples} replicas; coefficients with IQR "
            f"excluding zero: {int(bs.significant_mask.sum())} / "
            f"{len(bs.coefficient_summaries)}</p>")
        parts.append(_table(
            ["metric", "q1", "median", "q3"],
            [(k, f"{s.q1:.6g}", f"{s.median:.6g}", f"{s.q3:.6g}")
             for k, s in sorted(bs.metric_summaries.items())]))

    if report.fitting is not None and report.fitting.metrics:
        parts.append("<h2>Learning curves</h2>")
        parts.append(_legend([("--s1", "train"), ("--s2", "holdout")]))
        for metric, curve in sorted(report.fitting.metrics.items()):
            parts.append(f"<h3>{_esc(metric)}</h3>")
            parts.append(_svg_lines(
                list(curve["portions"]),
                [("--s1", "train", list(curve["train"])),
                 ("--s2", "holdout", list(curve["test"]))],
                "training portion"))
    elif report.fitting is not None:
        parts.append("<h2>Learning curves</h2>")
        parts.append(f"<p class='note'>{_esc(report.fitting.message)}</p>")

    parts.append("</body></html>")
    return "".join(parts)
