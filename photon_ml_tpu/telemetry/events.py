"""The telemetry event vocabulary: one constant per operational anomaly
source.

Operators grep traces, run logs, and flight-recorder bundles by event
name; nothing rots a postmortem workflow faster than a fault site or dump
trigger whose events quietly renamed (or never existed).  This registry
pins the vocabulary: EVERY name in `utils.faults.SITES` and EVERY
registered flight-recorder trigger (`telemetry.flight.TRIGGERS`) must
have an entry here, mapping the registry name to the telemetry event
name its firing emits.  photonlint PH008 diffs the three registries
statically — a new fault site or trigger cannot land without declaring
its event surface, and a stale entry here fails the same check.

Fault sites all surface through the single `fault` instant event (with a
`site` attr — `utils.faults.FaultPlan.fire` emits it), so their entries
map to "fault".  Flight triggers surface through `flight_dump` (with a
`reason` attr).  The mapping is still per-name on purpose: the registry
diff is what PH008 checks, and a future site/trigger that wants its own
event name simply maps to it here.
"""
from __future__ import annotations

from typing import Dict

#: registry name -> telemetry event name emitted when it fires
EVENTS: Dict[str, str] = {
    # -- fault sites (utils.faults.SITES -> the `fault` instant event) ----
    "stage.fetch": "fault",
    "stage.transfer": "fault",
    "mesh.stage": "fault",
    "admm.stage": "fault",
    "checkpoint.write": "fault",
    "checkpoint.fsync": "fault",
    "model.save": "fault",
    "model.load": "fault",
    "solve.poison": "fault",
    "solve.local": "fault",
    "online.solve": "fault",
    "online.publish": "fault",
    "health.evaluate": "fault",
    "replog.append": "fault",
    "replog.read": "fault",
    "replica.apply": "fault",
    "store.fetch": "fault",
    "store.promote": "fault",
    "store.spill": "fault",
    "refit.compact": "fault",
    "refit.validate": "fault",
    "refit.swap": "fault",
    "shard.route": "fault",
    "shard.merge": "fault",
    "shard.catchup": "fault",
    # -- flight-recorder triggers (telemetry.flight.TRIGGERS ->
    #    the `flight_dump` instant event) --------------------------------
    "health.gate_trip": "flight_dump",
    "replica.failed": "flight_dump",
    "replica.unhealthy": "flight_dump",
    "model.rollback": "flight_dump",
    "serve.drain": "flight_dump",
    "serve.crash": "flight_dump",
    "shard.lost": "flight_dump",
}
