"""Cross-process trace propagation + multi-process trace merge.

PR 8's tracer stops at the process boundary: a scoring request that is
hedged by the front, scored on replica B, and whose feedback later
triggers a delta publish leaves four disconnected span trees in four run
logs.  This module makes one logical request ONE tree:

  PROPAGATION — the front mints a `request_id` per routed request and
  carries it as HTTP headers (`X-Photon-Trace` = request id,
  `X-Photon-Parent` = the sender's `pid:span_id` ref) through every hop:
  front routing/hedging -> replica scoring, /feedback -> the publisher's
  OnlineUpdater cycle -> the replication-log record -> every replica's
  apply.  Server-side handlers open a `serve_request` span via
  `server_span()`, which adopts the incoming id (or mints one for
  direct-to-replica traffic) and records the remote parent ref as a span
  attr; asynchronous hops (feedback rows buffered into a later update
  cycle, deltas applied from the log) carry the ids in `request_ids`
  attrs and in the log record's `trace` metadata.

  CLOCK ALIGNMENT — each process's run log anchors its perf-counter
  timeline at `wall0_unix_s` (the tracer's meta record).  Wall clocks on
  one host agree to ~µs, but the anchor pairs (perf_counter(), time())
  are sampled non-atomically, so the front refines them: every health
  probe is also an NTP-style clock probe (`offset ≈ remote_wall -
  (send+recv)/2`), emitted as `clock_probe` events.  The merge keeps the
  minimum-RTT probe per process — the tightest bound available without a
  time daemon.

  MERGE — `merge_run_logs([...run-log.jsonl])` stitches the per-process
  logs into one validated Perfetto/Chrome trace: real pids as Perfetto
  process tracks (named by role), globally-unique `pid:span_id` refs,
  flow events binding each request's spans across processes, and a
  connectivity + containment report (every sampled request one connected
  tree; children inside their parents after alignment).  The CLI face is
  `python -m photon_ml_tpu.cli.trace merge`.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

from photon_ml_tpu.telemetry import core as _core

#: the propagation headers (the "header grammar" in README/COMPONENTS)
TRACE_HEADER = "X-Photon-Trace"
PARENT_HEADER = "X-Photon-Parent"

_TLS = threading.local()


def new_request_id() -> str:
    """16 hex chars: unique across a fleet for any realistic horizon."""
    return uuid.uuid4().hex[:16]


def span_ref(span_id: Optional[int],
             pid: Optional[int] = None) -> Optional[str]:
    """A process-qualified span reference: "pid:span_id"."""
    if span_id is None:
        return None
    return f"{pid if pid is not None else os.getpid()}:{span_id}"


# -- thread-local request context ---------------------------------------------

def set_context(request_id: Optional[str],
                ref: Optional[str] = None) -> None:
    _TLS.request_id = request_id
    _TLS.ref = ref


def current_request_id() -> Optional[str]:
    return getattr(_TLS, "request_id", None)


def current_ref() -> Optional[str]:
    """The propagation parent ref for an outbound hop: the ref stored by
    the enclosing server_span / front request scope."""
    return getattr(_TLS, "ref", None)


def outbound_headers(request_id: Optional[str] = None,
                     ref: Optional[str] = None) -> Dict[str, str]:
    """Headers for an outbound HTTP hop.  Explicit values win (the front
    captures them on the request thread before handing sends to pool
    threads); otherwise the thread-local context applies.  Empty when
    there is nothing to propagate."""
    rid = request_id if request_id is not None else current_request_id()
    parent = ref if ref is not None else current_ref()
    out: Dict[str, str] = {}
    if rid:
        out[TRACE_HEADER] = rid
    if parent:
        out[PARENT_HEADER] = parent
    return out


class server_span:
    """`with distributed.server_span("serve_request", handler.headers,
    path="/score"):` — the server half of a propagated hop.

    Adopts the incoming request id (minting one when absent so
    direct-to-replica traffic is traceable too), opens a telemetry span
    carrying `request_id` (+ `remote_parent` when the peer sent one), and
    installs the thread-local context so deeper code — `feedback()`
    stamping buffered observations, nested outbound hops — sees the
    request identity.  Disarmed tracing costs the usual no-op span plus
    two thread-local writes."""

    __slots__ = ("_name", "_attrs", "_request_id", "_remote_parent",
                 "_span", "_prev")

    def __init__(self, name: str, headers=None, request_id: Optional[str]
                 = None, remote_parent: Optional[str] = None, **attrs):
        get = (headers.get if headers is not None else lambda _k: None)
        self._request_id = (request_id or get(TRACE_HEADER)
                            or new_request_id())
        self._remote_parent = remote_parent or get(PARENT_HEADER)
        self._name = name
        self._attrs = attrs

    @property
    def request_id(self) -> str:
        return self._request_id

    def __enter__(self) -> "server_span":
        attrs = dict(self._attrs)
        attrs["request_id"] = self._request_id
        if self._remote_parent:
            attrs["remote_parent"] = self._remote_parent
        tracer = _core.active_tracer()
        if tracer is not None:
            self._span = tracer.push(self._name, attrs)
            ref = span_ref(self._span.span_id)
        else:
            self._span = None
            ref = self._remote_parent
        self._prev = (current_request_id(), current_ref())
        set_context(self._request_id, ref)
        return self

    def __exit__(self, *exc):
        set_context(*self._prev)
        if self._span is not None:
            self._span._tracer.pop(self._span)
        return False


def clock_info() -> Dict[str, object]:
    """The clock-probe payload a serving process embeds in /healthz:
    enough for a prober to identify this process's timeline (pid + role)
    and estimate its wall-clock offset."""
    tracer = _core.active_tracer()
    return {"pid": os.getpid(),
            "proc": tracer.proc if tracer is not None else "proc",
            "wall_s": time.time()}


# -- run-log parsing + merge --------------------------------------------------

def parse_run_log(path: str) -> Dict[str, object]:
    """One JSONL run log -> {"meta", "spans", "events"}.  Torn final
    lines (a killed process mid-write) are dropped, matching the
    replication log's read discipline."""
    meta = None
    spans: List[dict] = []
    events: List[dict] = []
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                break  # torn tail: the process died mid-append
            raise
        kind = rec.get("kind")
        if kind == "meta" and meta is None:
            meta = rec
        elif kind == "span":
            spans.append(rec)
        elif kind == "event":
            events.append(rec)
    if meta is None:
        raise ValueError(
            f"run log {path!r} has no process_meta record — it predates "
            "multi-process tracing (re-export with this version) or is "
            "not a telemetry run log")
    return {"meta": meta, "spans": spans, "events": events, "path": path}


def _collect_offsets(logs: List[dict]) -> Dict[int, Tuple[float, float]]:
    """clock_probe events -> {remote pid: (offset_s, rtt_s)}, keeping the
    minimum-RTT probe per process (the tightest NTP-style bound)."""
    best: Dict[int, Tuple[float, float]] = {}
    for lg in logs:
        for ev in lg["events"]:
            if ev.get("name") != "clock_probe":
                continue
            attrs = ev.get("attrs", {})
            try:
                pid = int(attrs["pid"])
                offset = float(attrs["offset_s"])
                rtt = float(attrs["rtt_s"])
            except (KeyError, TypeError, ValueError):
                continue
            if pid not in best or rtt < best[pid][1]:
                best[pid] = (offset, rtt)
    return best


def _span_request_ids(attrs: dict) -> List[str]:
    """The request ids a span belongs to: its own `request_id` plus any
    `request_ids` list an aggregation span (online_update, replica_apply)
    carries as a comma-joined string."""
    out: List[str] = []
    rid = attrs.get("request_id")
    if rid:
        out.append(str(rid))
    multi = attrs.get("request_ids")
    if multi:
        out.extend(r for r in str(multi).split(",") if r)
    return out


class _Union:
    """Tiny union-find for the per-request connectivity check."""

    def __init__(self):
        self.parent: Dict[str, str] = {}

    def find(self, x: str) -> str:
        self.parent.setdefault(x, x)
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]
            x = self.parent[x]
        return x

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb


def merge_run_logs(paths: Iterable[str], out_path: Optional[str] = None,
                   containment_slack_s: float = 0.025
                   ) -> Dict[str, object]:
    """Stitch per-process run logs into one Perfetto trace + report.

    Returns {"processes", "spans", "events", "requests", "connected_ok",
    "containment", "clock_offsets", "problems", "trace"} — `trace` is
    the Chrome-trace payload (also written atomically to `out_path` when
    given), `problems` is `validate_chrome_trace`'s verdict on it.
    """
    from photon_ml_tpu.telemetry.export import validate_chrome_trace

    logs = [parse_run_log(p) for p in paths]
    offsets = _collect_offsets(logs)

    # wall-anchor every record; apply the probe offset so every process
    # lands on the PROBER's (front's) timeline
    procs: List[dict] = []
    all_spans: List[dict] = []   # each: ref/pid/tid/name/ts/dur/attrs/parent
    all_events: List[dict] = []
    for lg in logs:
        meta = lg["meta"]
        pid = int(meta["pid"])
        offset, rtt = offsets.get(pid, (0.0, None))
        wall0 = float(meta["wall0_unix_s"]) - offset
        procs.append({"pid": pid, "proc": meta.get("proc", "proc"),
                      "path": lg["path"], "offset_s": offset,
                      "probe_rtt_s": rtt,
                      "spans": len(lg["spans"]), "events": len(lg["events"])})
        for rec in lg["spans"]:
            all_spans.append({
                "ref": span_ref(rec["span"], pid),
                "parent": span_ref(rec.get("parent"), pid),
                "pid": pid, "tid": rec["tid"],
                "thread": rec.get("thread"),
                "name": rec["name"],
                "ts": wall0 + float(rec["t0_s"]),
                "dur": float(rec.get("dur_s") or 0.0),
                "attrs": rec.get("attrs", {}),
            })
        for rec in lg["events"]:
            all_events.append({
                "ref": span_ref(rec.get("span"), pid),
                "pid": pid, "tid": rec["tid"], "name": rec["name"],
                "ts": wall0 + float(rec["t_s"]),
                "attrs": rec.get("attrs", {}),
            })
    if not all_spans and not all_events:
        raise ValueError("nothing to merge: every run log was empty")
    t_min = min([s["ts"] for s in all_spans]
                + [e["ts"] for e in all_events])

    by_ref = {s["ref"]: s for s in all_spans}

    # -- request connectivity -------------------------------------------------
    request_spans: Dict[str, List[dict]] = {}
    for s in all_spans:
        for rid in _span_request_ids(s["attrs"]):
            request_spans.setdefault(rid, []).append(s)

    def ancestor_in(span: dict, member: set) -> Optional[str]:
        """Walk parent + remote_parent links up; first ancestor ref that
        is in `member` (connectivity may pass through unrelated spans)."""
        seen = set()
        cur = span
        while True:
            nxt = cur["parent"] or cur["attrs"].get("remote_parent")
            if not nxt or nxt in seen:
                return None
            seen.add(nxt)
            if nxt in member:
                return nxt
            cur = by_ref.get(nxt)
            if cur is None:
                return None

    requests: Dict[str, dict] = {}
    flows: List[dict] = []
    for rid, spans in sorted(request_spans.items()):
        member = {s["ref"] for s in spans}
        uf = _Union()
        for s in spans:
            uf.find(s["ref"])
            anc = ancestor_in(s, member)
            if anc:
                uf.union(s["ref"], anc)
        # asynchronous same-process hops (serve_request -> online_update)
        # chain by start time within each pid
        by_pid: Dict[int, List[dict]] = {}
        for s in spans:
            by_pid.setdefault(s["pid"], []).append(s)
        for pid_spans in by_pid.values():
            pid_spans.sort(key=lambda s: s["ts"])
            for a, b in zip(pid_spans, pid_spans[1:]):
                uf.union(a["ref"], b["ref"])
        roots = {uf.find(s["ref"]) for s in spans}
        requests[rid] = {
            "spans": len(spans),
            "processes": sorted({s["pid"] for s in spans}),
            "span_names": sorted({s["name"] for s in spans}),
            "connected": len(roots) == 1,
        }
        # flow events: one chain per request, ordered by aligned time,
        # so Perfetto draws the request crossing processes
        chain = sorted(spans, key=lambda s: s["ts"])
        if len(chain) >= 2:
            for i, s in enumerate(chain):
                ph = "s" if i == 0 else ("f" if i == len(chain) - 1
                                         else "t")
                flow = {"name": f"req:{rid}", "cat": "photon-flow",
                        "ph": ph, "id": int(rid[:8], 16),
                        "pid": s["pid"], "tid": s["tid"],
                        "ts": round((s["ts"] - t_min) * 1e6, 3)}
                if ph == "f":
                    flow["bp"] = "e"
                flows.append(flow)

    # -- containment: synchronous cross-process children inside parents ------
    checked = 0
    violations: List[dict] = []
    for s in all_spans:
        rp = s["attrs"].get("remote_parent")
        if not rp:
            continue
        parent = by_ref.get(rp)
        if parent is None or not str(parent["name"]).startswith("front_"):
            continue  # async links (log replay) are not containment-bound
        checked += 1
        lo = parent["ts"] - containment_slack_s
        hi = parent["ts"] + parent["dur"] + containment_slack_s
        if s["ts"] < lo or s["ts"] + s["dur"] > hi:
            violations.append({
                "child": s["ref"], "child_name": s["name"],
                "parent": rp, "parent_name": parent["name"],
                "child_window": [round(s["ts"] - t_min, 6),
                                 round(s["ts"] + s["dur"] - t_min, 6)],
                "parent_window": [round(parent["ts"] - t_min, 6),
                                  round(parent["ts"] + parent["dur"]
                                        - t_min, 6)],
            })

    # -- chrome events --------------------------------------------------------
    events: List[dict] = []
    for p in procs:
        events.append({"name": "process_name", "ph": "M", "ts": 0,
                       "pid": p["pid"], "tid": 0,
                       "args": {"name": f"{p['proc']} ({p['pid']})"}})
    threads_seen: Dict[Tuple[int, object], Optional[str]] = {}
    for s in all_spans:
        threads_seen.setdefault((s["pid"], s["tid"]), s["thread"])
        events.append({
            "name": s["name"], "cat": "photon", "ph": "X",
            "ts": round((s["ts"] - t_min) * 1e6, 3),
            "dur": round(max(s["dur"], 0.0) * 1e6, 3),
            "pid": s["pid"], "tid": s["tid"],
            "args": {"span": s["ref"], "parent": s["parent"],
                     **s["attrs"]},
        })
    for e in all_events:
        threads_seen.setdefault((e["pid"], e["tid"]), None)
        events.append({
            "name": e["name"], "cat": "photon", "ph": "i", "s": "t",
            "ts": round((e["ts"] - t_min) * 1e6, 3),
            "pid": e["pid"], "tid": e["tid"],
            "args": {"span": e["ref"], **e["attrs"]},
        })
    events.extend(flows)
    for (pid, tid), name in sorted(threads_seen.items(),
                                   key=lambda kv: (kv[0][0], str(kv[0][1]))):
        if name:
            events.append({"name": "thread_name", "ph": "M", "ts": 0,
                           "pid": pid, "tid": tid, "args": {"name": name}})

    payload = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"producer": "photon_ml_tpu.telemetry."
                                         "distributed",
                             "t_min_unix_s": t_min,
                             "processes": [
                                 {k: p[k] for k in ("pid", "proc",
                                                    "offset_s")}
                                 for p in procs]}}
    problems = validate_chrome_trace(payload)
    if out_path is not None:
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"))
        os.replace(tmp, out_path)

    return {
        "path": out_path,
        "processes": procs,
        "spans": len(all_spans),
        "events": len(all_events),
        "flow_events": len(flows),
        "requests": requests,
        "connected_ok": (all(r["connected"] for r in requests.values())
                         if requests else False),
        "containment": {"checked": checked,
                        "slack_s": containment_slack_s,
                        "violations": violations,
                        "ok": checked > 0 and not violations},
        "clock_offsets": {str(pid): {"offset_s": off, "rtt_s": rtt}
                          for pid, (off, rtt) in sorted(offsets.items())},
        "problems": problems,
        "trace": payload,
    }
