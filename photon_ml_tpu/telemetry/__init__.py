"""Unified telemetry: span tracing, a metrics registry, and exportable
run timelines across training and serving.

Three layers, one import:

  * the SPAN TRACER (`core`) — `telemetry.span(name, **attrs)` produces a
    hierarchical, thread-aware trace of a run (outer iterations ->
    coordinate visits -> inner solves / chunk staging / checkpoint writes
    / serving batches), with `utils.faults.fire()`-style disarm semantics:
    a module-global None check and a shared no-op singleton when off —
    zero traces, zero device reads, nothing allocated.
  * the METRICS REGISTRY (`metrics`) — counters/gauges/bounded-reservoir
    histograms that the existing accounting surfaces (PhaseTimings'
    host-blocked time, StreamStats, TransferStats, ServingMetrics,
    quarantine/containment events, checkpoint/retry counters, the
    `jax.retraces` fresh-compile counter) publish through, so ONE
    `telemetry.snapshot()` returns everything.  Always live (an increment
    costs what the bespoke accumulators already cost).
  * EXPORTERS (`export`) — Chrome-trace/Perfetto JSON (`--trace-out` on
    cli.train and bench.py), a JSONL run log correlated with EventEmitter
    events and fault/quarantine/recovery records by span id, and
    Prometheus text exposition (mounted at `/metrics` on the serving HTTP
    service).

Arming:

    tracer = telemetry.install(run_log="out/run-log.jsonl")
    ... run the fit ...
    telemetry.write_chrome_trace("out/trace.json")
    telemetry.shutdown()

or scoped: `with telemetry.enabled() as tracer: ...`.

photonlint PH007 enforces that hot-path modules time spans through this
package (PhaseTimings / `timings.clock()`), never raw
`time.perf_counter()` — one trace, not thirty stopwatches.
"""
from photon_ml_tpu.telemetry.core import (  # noqa: F401
    MAX_RECORDS, NOOP_SPAN, SpanRecord, Tracer, active_tracer, armed,
    current_span_id, enabled, event, install, last_tracer, pop, push,
    retrace_count, set_observer, shutdown, span,
)
from photon_ml_tpu.telemetry.export import (  # noqa: F401
    CHROME_REQUIRED_KEYS, chrome_trace_events, prometheus_text,
    render_prometheus_snapshot, validate_chrome_trace,
)
from photon_ml_tpu.telemetry.export import (
    write_chrome_trace as _write_chrome_trace,
)
from photon_ml_tpu.telemetry.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, LabeledCounter, MetricsRegistry, counter,
    default_registry, gauge, histogram,
)
from photon_ml_tpu.telemetry import distributed, events, flight  # noqa: F401
from photon_ml_tpu.telemetry.timings import PhaseTimings, clock  # noqa: F401

# collectors: named callables whose dict results ride along in snapshot()
# (a ScoringService registers its metrics snapshot here so one call
# returns training AND serving state); unregister on close.
_COLLECTORS = {}


def register_collector(name: str, fn) -> None:
    _COLLECTORS[name] = fn


def unregister_collector(name: str) -> None:
    _COLLECTORS.pop(name, None)


def snapshot() -> dict:
    """Everything: the default registry's instruments, every registered
    collector, and (when a tracer is or was armed) its record counts.
    All values JSON-safe — this dict lands verbatim in BENCH_*.json and
    training-summary.json."""
    out = {"metrics": default_registry().snapshot()}
    for name, fn in sorted(_COLLECTORS.items()):
        try:
            out[name] = fn()
        except Exception as e:  # a dead collector must not kill a snapshot
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    tracer = last_tracer()
    if tracer is not None:
        out["tracer"] = tracer.stats()
    return out


def write_chrome_trace(path: str, tracer=None) -> dict:
    """Export the active (or most recently finished) tracer's timeline."""
    tracer = tracer if tracer is not None else last_tracer()
    if tracer is None:
        raise RuntimeError("no tracer has been installed this process — "
                           "call telemetry.install() before the run")
    return _write_chrome_trace(tracer, path)
