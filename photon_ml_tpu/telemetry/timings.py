"""PhaseTimings: contiguous per-fit span accounting, bridged into the
span tracer.

Moved here from game/coordinate_descent.py: photonlint PH007 forbids raw
`time.perf_counter()` span timing inside the hot-path modules, and this is
the ONE sanctioned implementation — every timed phase of a fit lands both
in the per-fit dict (the cli summary / bench tables, armed or not) and,
when the tracer is armed, in the hierarchical trace as a named span.

`clock()` is the sanctioned raw timestamp for hot modules that need a
bare duration (the disarmed-overhead bench times itself with it too).
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict

from photon_ml_tpu.telemetry import core as _core


def clock() -> float:
    """Monotonic high-resolution seconds (the telemetry time base)."""
    return time.perf_counter()


class PhaseTimings(dict):
    """Accumulating span timer (reference: Timer/Timed spans at every driver
    stage, photon-lib/.../util/Timer.scala:32-234 used ~30x).  Spans are
    CONTIGUOUS over the descent loop so their sum accounts for the whole
    fit wall-clock — an unattributed gap means an untimed stage, which is
    exactly what round 3's bench suffered from.

    `host_blocked` tracks, per span label, the seconds the host spent
    BLOCKED on device readbacks (scalar syncs, `float()` objective fetches,
    [n]-array transfers into numpy evaluators, the pipelined boundary
    flush).  host_blocked_total()/wall is the host-blocked fraction bench
    reports per config — the quantity pipelining exists to shrink; it also
    lands in the `train.host_blocked_s`/`train.host_blocked_frac` gauges
    at fit end (game/coordinate_descent.py).

    When the tracer is armed, `span(label, name=..., **attrs)` also emits
    a telemetry span (`name` defaults to the label) so the per-fit dict
    and the exported timeline are the same measurement, not two."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.host_blocked: Dict[str, float] = {}

    @contextlib.contextmanager
    def span(self, label: str, host_blocked: bool = False,
             name: str = None, **attrs):
        tspan = _core.span(name if name is not None else label, **attrs)
        t0 = clock()
        try:
            with tspan:
                yield
        finally:
            dt = clock() - t0
            self[label] = self.get(label, 0.0) + dt
            if host_blocked:
                self.add_blocked(label, dt)

    @contextlib.contextmanager
    def blocked(self, label: str):
        """Time a host-blocking readback into `host_blocked` WITHOUT
        opening a new accounting span (the enclosing span already covers
        the wall time)."""
        t0 = clock()
        try:
            yield
        finally:
            self.add_blocked(label, clock() - t0)

    def add_blocked(self, label: str, seconds: float) -> None:
        self.host_blocked[label] = self.host_blocked.get(label, 0.0) + seconds

    def host_blocked_total(self) -> float:
        return float(sum(self.host_blocked.values()))

    def total(self) -> float:
        return float(sum(self.values()))
