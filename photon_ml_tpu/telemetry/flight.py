"""Flight recorder: the last N seconds of every process, on disk before
anyone asks.

Postmortems of a serving fleet die on a timing problem: the interesting
window is the seconds BEFORE the health gate tripped / the replica was
marked unhealthy / the process caught SIGTERM, and by the time an operator
attaches, that window is gone.  The flight recorder keeps it resident: an
always-on BOUNDED ring of recent telemetry records (closed spans, instant
events, and photon log lines), fed by the armed tracer's observer tap
(`core.set_observer`) and a logging handler — and dumps the whole ring to
a durable, correlated bundle when a registered trigger fires.

DISARM SEMANTICS (the `faults.fire()` contract): with no recorder
installed, `trigger()`/`record_event()` are a module-global None check and
return.  Armed, a record is one deque append (O(1), bounded memory) — the
armed-overhead bench gate (`bench.py --fleetobs`, <= 1.1x disarmed scoring
p99, zero fresh XLA traces) holds the recorder to the same hot-path
discipline as the tracer.

TRIGGERS is the registry of dump reasons, the flight twin of
`utils.faults.SITES`: every trigger name must have a telemetry event
constant in `telemetry/events.py` (photonlint PH008 diffs the registries),
so the trigger taxonomy cannot drift from the event vocabulary operators
grep for.

Correlation across processes: a trigger mints a `trigger_id`; the fleet
front broadcasts it (`POST /flight/dump`) to every reachable replica when
it fires a fleet-level trigger (a replica leaving rotation), so the
bundles from all live processes share the id and can be laid side by
side.  Bundle files are written atomically (`utils.durable`) as
`flight-<trigger_id>-<proc>-<pid>.json`.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from photon_ml_tpu.telemetry import core as _core

logger = logging.getLogger("photon_ml_tpu")

#: registered dump triggers: name -> what fires it.  The flight twin of
#: `utils.faults.SITES` — photonlint PH008 enforces that every name here
#: has a telemetry event constant in telemetry/events.py.
TRIGGERS: Dict[str, str] = {
    "health.gate_trip": "a model-health gate tripped (health/monitor.py)",
    "replica.failed": "a replica marked itself failed (fatal apply)",
    "replica.unhealthy": "the front took a replica out of rotation",
    "model.rollback": "a model rollback executed on the live registry",
    "serve.drain": "SIGTERM graceful drain of a serving process",
    "serve.crash": "a serving process is dying on an unhandled error",
    "shard.lost": "an entity shard's last healthy replica left rotation",
}

#: default ring capacity (records, not bytes): spans + events + log lines
RING_RECORDS = 4096

#: log-line length cap inside the ring (tracebacks can be huge)
MAX_LOG_CHARS = 500


class _RingLogHandler(logging.Handler):
    """Feeds photon log lines into the recorder ring (WARNING+ by
    default: the anomaly trail, not the request firehose)."""

    def __init__(self, recorder: "FlightRecorder",
                 level: int = logging.WARNING):
        super().__init__(level=level)
        self._recorder = recorder

    def emit(self, record):
        try:
            msg = record.getMessage()
            if len(msg) > MAX_LOG_CHARS:
                msg = msg[:MAX_LOG_CHARS] + "..."
            self._recorder._append({
                "kind": "log", "level": record.levelname,
                "logger": record.name, "message": msg,
                "wall_s": record.created})
        except Exception:  # observability must never kill the observed
            pass


class FlightRecorder:
    """One process's bounded ring + dump machinery.  Install via
    `flight.install(dump_dir, proc=...)`; all methods are thread-safe."""

    def __init__(self, dump_dir: Optional[str] = None,
                 proc: str = "proc", ring_records: int = RING_RECORDS,
                 log_level: int = logging.WARNING):
        self.dump_dir = dump_dir
        self.proc = proc
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=int(ring_records))
        self.dumps = 0
        self.recorded = 0
        self._log_handler = _RingLogHandler(self, level=log_level)
        logger.addHandler(self._log_handler)

    # -- recording (the hot path) ------------------------------------------

    def _append(self, record: dict) -> None:
        with self._lock:
            self._ring.append(record)
            self.recorded += 1

    def observe(self, kind: str, record: dict, tracer) -> None:
        """The tracer observer tap (core.set_observer): closed spans and
        instant events land in the ring stamped with wall time."""
        rel = record.get("t0_s", record.get("t_s", 0.0))
        self._append({"kind": kind, "wall_s": tracer._wall0 + rel,
                      **{k: v for k, v in record.items()
                         if k not in ("kind",)}})

    def record_event(self, name: str, **attrs) -> None:
        """A recorder-only instant (used by trigger paths so the ring
        itself documents why it was dumped)."""
        self._append({"kind": "event", "name": name, "wall_s": time.time(),
                      "attrs": {k: str(v) for k, v in attrs.items()}})

    # -- dumping ------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str, trigger_id: str,
             attrs: Optional[dict] = None) -> Optional[str]:
        """Write the ring to a durable bundle; returns the path (None
        when no dump_dir is configured — the ring stays in memory).
        Never raises: a failing dump logs and returns None."""
        from photon_ml_tpu import telemetry
        from photon_ml_tpu.utils import durable
        records = self.snapshot()
        bundle = {
            "format_version": 1,
            "reason": reason,
            "trigger_id": trigger_id,
            "proc": self.proc,
            "pid": self.pid,
            "dumped_at_unix_s": time.time(),
            "attrs": {k: str(v) for k, v in (attrs or {}).items()},
            "window_s": ([min(r.get("wall_s", 0.0) for r in records),
                          max(r.get("wall_s", 0.0) for r in records)]
                         if records else None),
            "records": records,
            "metrics": telemetry.snapshot(),
        }
        with self._lock:
            self.dumps += 1
        if self.dump_dir is None:
            logger.warning("flight recorder: trigger %r (%s) fired but no "
                           "dump directory is configured — the ring stays "
                           "in memory only", reason, trigger_id)
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f"flight-{trigger_id}-{self.proc}-{self.pid}.json")
            durable.atomic_write_json(path, bundle)
            logger.warning("flight recorder: dumped %d record(s) to %s "
                           "(reason=%s)", len(records), path, reason)
            return path
        except Exception as e:  # a failing dump must not kill the trigger
            logger.error("flight recorder: dump for %r FAILED: %s",
                         reason, e)
            return None

    def close(self) -> None:
        logger.removeHandler(self._log_handler)


# -- process-global activation (faults.install_plan-style) --------------------

_ACTIVE: Optional[FlightRecorder] = None


def active_recorder() -> Optional[FlightRecorder]:
    return _ACTIVE


def armed() -> bool:
    return _ACTIVE is not None


def install(dump_dir: Optional[str] = None, proc: str = "proc",
            ring_records: int = RING_RECORDS,
            log_level: int = logging.WARNING) -> FlightRecorder:
    """Arm the flight recorder process-globally (last-wins) and tap the
    tracer's record stream."""
    global _ACTIVE
    prev = _ACTIVE
    recorder = FlightRecorder(dump_dir=dump_dir, proc=proc,
                              ring_records=ring_records,
                              log_level=log_level)
    _ACTIVE = recorder
    _core.set_observer(recorder.observe)
    if prev is not None:
        prev.close()
    return recorder


def shutdown() -> Optional[FlightRecorder]:
    global _ACTIVE
    recorder, _ACTIVE = _ACTIVE, None
    _core.set_observer(None)
    if recorder is not None:
        recorder.close()
    return recorder


class enabled:
    """`with flight.enabled(dump_dir) as rec:` — scoped arming for tests
    and bench legs."""

    def __init__(self, dump_dir: Optional[str] = None, proc: str = "proc",
                 ring_records: int = RING_RECORDS):
        self._kw = dict(dump_dir=dump_dir, proc=proc,
                        ring_records=ring_records)

    def __enter__(self) -> FlightRecorder:
        self.recorder = install(**self._kw)
        return self.recorder

    def __exit__(self, *exc):
        if _ACTIVE is self.recorder:
            shutdown()
        else:
            self.recorder.close()


def new_trigger_id(reason: str) -> str:
    """Trigger ids are sortable and collision-safe across one fleet:
    millisecond wall time + pid (the minting process's)."""
    safe = reason.replace(".", "-")
    return f"{safe}-{int(time.time() * 1e3)}-{os.getpid()}"


def trigger(reason: str, trigger_id: Optional[str] = None,
            **attrs) -> Optional[str]:
    """Fire a registered trigger: record it in the ring, emit the
    matching telemetry event, dump the bundle.  Zero-cost disarmed
    (module-global None check).  Returns the bundle path (or None)."""
    recorder = _ACTIVE
    if recorder is None:
        return None
    if reason not in TRIGGERS:
        raise ValueError(
            f"unknown flight trigger {reason!r} — register it in "
            f"telemetry.flight.TRIGGERS (known: {sorted(TRIGGERS)})")
    tid = trigger_id or new_trigger_id(reason)
    from photon_ml_tpu import telemetry
    telemetry.event("flight_dump", reason=reason, trigger_id=tid,
                    **{k: str(v) for k, v in attrs.items()})
    recorder.record_event("flight_dump", reason=reason, trigger_id=tid,
                          **attrs)
    return recorder.dump(reason, tid, attrs=attrs)
