"""Telemetry exporters: Chrome-trace/Perfetto JSON and Prometheus text.

Chrome trace (the `--trace-out trace.json` format on cli.train and
bench.py): the Trace Event Format's JSON-object form — `{"traceEvents":
[...]}` with complete ("X") events for spans and instant ("i") events for
point records.  Every event carries the format's required keys (`name`,
`ph`, `ts`, `pid`, `tid`; `dur` on "X") plus `args.span`/`args.parent` so
the span tree is validatable without reconstructing it from timestamps.
Open a trace at https://ui.perfetto.dev (drag the file in) or
chrome://tracing.

Prometheus text (the serving `/metrics` endpoint): exposition format
0.0.4.  Counters render as `photon_<name>_total`, gauges as
`photon_<name>`, histograms as summaries (`{quantile="..."}` series plus
`_sum`/`_count`) — quantiles come from the registry's bounded reservoir,
so a scrape is O(reservoir), never O(requests).
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

from photon_ml_tpu.telemetry.core import Tracer
from photon_ml_tpu.telemetry.metrics import MetricsRegistry

#: keys the Trace Event Format requires on every event (+ "dur" for "X")
CHROME_REQUIRED_KEYS = ("name", "ph", "ts", "pid", "tid")


def chrome_trace_events(tracer: Tracer) -> List[dict]:
    """Tracer records -> trace-event dicts (µs timestamps, one pid)."""
    pid = os.getpid()
    out: List[dict] = []
    threads = {}
    now = tracer.now()
    for record in list(tracer.spans):
        threads.setdefault(record.tid, record.thread_name)
        dur = record.dur_s if record.dur_s is not None else now - record.t0
        out.append({
            "name": record.name, "cat": "photon", "ph": "X",
            "ts": round(record.t0 * 1e6, 3),
            "dur": round(max(dur, 0.0) * 1e6, 3),
            "pid": pid, "tid": record.tid,
            "args": {"span": record.span_id, "parent": record.parent_id,
                     **record.attrs},
        })
    for record in list(tracer.events):
        threads.setdefault(record["tid"], None)
        out.append({
            "name": record["name"], "cat": "photon", "ph": "i", "s": "t",
            "ts": round(record["t_s"] * 1e6, 3),
            "pid": pid, "tid": record["tid"],
            "args": {"span": record["span"], **record["attrs"]},
        })
    # thread-name metadata rows make the Perfetto tracks self-describing
    for tid, name in sorted(threads.items(), key=lambda kv: str(kv[0])):
        if name:
            out.append({"name": "thread_name", "ph": "M", "ts": 0,
                        "pid": pid, "tid": tid, "args": {"name": name}})
    return out


def write_chrome_trace(tracer: Tracer, path: str) -> dict:
    """Write the trace JSON (atomically — a kill mid-export must not leave
    a torn half-file that Perfetto rejects with an opaque parse error).
    Returns summary stats."""
    events = chrome_trace_events(tracer)
    payload = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"producer": "photon_ml_tpu.telemetry",
                             "wall0_unix_s": tracer._wall0}}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, separators=(",", ":"))
    os.replace(tmp, path)
    return {"path": path, "events": len(events),
            "spans": len(tracer.spans), "instants": len(tracer.events),
            "dropped": tracer.dropped}


def validate_chrome_trace(payload: dict) -> List[str]:
    """Problems with a trace dict against the format's required keys
    (empty list = valid).  Used by the --trace bench gate and the smoke
    test rather than trusting the writer to have stayed honest."""
    problems: List[str] = []
    events = payload.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    for i, ev in enumerate(events):
        for key in CHROME_REQUIRED_KEYS:
            if key not in ev:
                problems.append(f"event {i} ({ev.get('name')!r}) missing "
                                f"required key {key!r}")
        if ev.get("ph") == "X" and "dur" not in ev:
            problems.append(f"complete event {i} ({ev.get('name')!r}) "
                            "missing 'dur'")
    return problems


# -- Prometheus text exposition ------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "photon_" + _NAME_RE.sub("_", name)


def _prom_value(v) -> str:
    if v is None:
        return "NaN"
    return repr(float(v)) if isinstance(v, float) else str(v)


def _esc_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _label_str(labels: Optional[Dict[str, str]],
               extra: Optional[Dict[str, str]] = None) -> str:
    """{k: v} -> '{k="v",...}' (empty string for no labels)."""
    merged: Dict[str, str] = {}
    merged.update(labels or {})
    merged.update(extra or {})
    if not merged:
        return ""
    inner = ",".join(f'{_NAME_RE.sub("_", k)}="{_esc_label(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _parse_label_key(key: str) -> Dict[str, str]:
    """A LabeledCounter snapshot key ('k=v,k2=v2') -> {k: v}.  Splits on
    ',' then the FIRST '=' per segment — label values (replica URLs) may
    contain '=' but never ','."""
    out: Dict[str, str] = {}
    for seg in key.split(","):
        k, _, v = seg.partition("=")
        out[k] = v
    return out


def render_prometheus_snapshot(snap: Dict[str, Dict],
                               lines: List[str],
                               labels: Optional[Dict[str, str]] = None,
                               seen_types: Optional[set] = None) -> None:
    """One registry SNAPSHOT -> exposition lines, every series stamped
    with the constant `labels` (the federated surface's per-replica
    `instance` label).  `seen_types` dedups `# TYPE` headers when several
    snapshots of the same instrument family render into one page."""
    seen = seen_types if seen_types is not None else set()

    def typ(p: str, kind: str) -> None:
        if p not in seen:
            seen.add(p)
            lines.append(f"# TYPE {p} {kind}")

    lab = _label_str(labels)
    for name, value in snap.get("counters", {}).items():
        p = _prom_name(name) + "_total"
        typ(p, "counter")
        lines.append(f"{p}{lab} {_prom_value(value)}")
    for name, value in snap.get("gauges", {}).items():
        p = _prom_name(name)
        typ(p, "gauge")
        lines.append(f"{p}{lab} {_prom_value(value)}")
    for name, series in snap.get("labeled", {}).items():
        p = _prom_name(name) + "_total"
        typ(p, "counter")
        if not series:
            # a registered family with no observed series still exposes
            # one zero sample, so scrapers (and the JSON/Prometheus
            # parity contract) see the instrument before first use
            lines.append(f"{p}{lab} 0")
        for key, value in sorted(series.items()):
            lines.append(f"{p}{_label_str(labels, _parse_label_key(key))} "
                         f"{_prom_value(value)}")
    for name, h in snap.get("histograms", {}).items():
        p = _prom_name(name)
        typ(p, "summary")
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.95, "p95"),
                       (0.99, "p99")):
            lines.append(f"{p}{_label_str(labels, {'quantile': str(q)})} "
                         f"{_prom_value(h[key])}")
        lines.append(f"{p}_sum{lab} {_prom_value(h['sum'])}")
        lines.append(f"{p}_count{lab} {h['count']}")
        if h["max"] is not None:
            typ(f"{p}_max", "gauge")
            lines.append(f"{p}_max{lab} {_prom_value(h['max'])}")


def prometheus_text(registry: MetricsRegistry,
                    extra_info: Optional[Dict[str, str]] = None,
                    labels: Optional[Dict[str, str]] = None) -> str:
    """Registry -> Prometheus exposition text (version 0.0.4).
    `extra_info` renders as a `photon_info{k="v",...} 1` series (the
    conventional carrier for e.g. the serving model version);
    `labels` stamps every series (the federated surface's instance
    label)."""
    lines: List[str] = []
    render_prometheus_snapshot(registry.snapshot(), lines, labels=labels)
    if extra_info:
        lines.append("# TYPE photon_info gauge")
        lines.append(f"photon_info{_label_str(extra_info)} 1")
    return "\n".join(lines) + "\n"
