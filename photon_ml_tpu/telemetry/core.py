"""Span tracer: hierarchical, thread-aware run timelines with fault-style
disarm semantics.

Every perf PR so far justified itself through a bespoke bench-only counter
(PhaseTimings, StreamStats, TransferStats, ServingMetrics, ...); none of
them compose into one picture of where a fit or a serving process spends
its time.  This module is the composing layer:

  * `span(name, **attrs)` — a context manager producing one node of a
    hierarchical trace.  Spans nest per THREAD (thread-local stacks), so
    the training loop, the streaming Prefetcher, the AsyncCheckpointer
    writer, and the serving micro-batcher each get their own track with
    correct parent/child edges inside it.
  * `push(name, **attrs)` / `pop(handle)` — the explicit form for regions
    that cannot wrap a `with` block (the descent loop's outer-iteration /
    coordinate-visit levels).  `pop` is self-healing: it closes any spans
    left open below its handle, and `Tracer.finish()` closes whatever an
    exception path abandoned, so a preempted fit still exports a complete
    timeline.
  * `event(name, **attrs)` — an instant event attached to the CURRENT
    span (fault injections, quarantine rollbacks, checkpoint recoveries,
    EventEmitter events); the span id correlates it with the JSONL run
    log and the Chrome trace.
  * the compile watch — when armed (the default), `jax_log_compiles`
    records become `compile` instant events carrying the triggering
    shape/signature message, and the `jax.retraces` counter increments:
    the runtime counterpart of photonlint PH002.

DISARM SEMANTICS (the contract the hot paths rely on, same discipline as
`utils.faults.fire`): with no tracer installed, `span()` is a module-global
None check returning a shared no-op singleton — no span objects, no list
appends, no fresh XLA traces, nothing on the device hot path.  The
compile-count and disarmed-overhead bench legs (bench.py --trace) gate
this.  Armed tracing touches HOST values only (names, ints, floats); it
never reads a device array, so it adds zero sync points (photonlint PH001
stays clean over every instrumented module).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from photon_ml_tpu.telemetry import metrics as _metrics

logger = logging.getLogger("photon_ml_tpu")

#: hard cap on retained finished spans/events; beyond it the tracer counts
#: drops instead of growing without bound (a week-long serving process must
#: not OOM on its own observability)
MAX_RECORDS = 200_000

#: attr-value length cap in exported records (compile messages carry whole
#: shape signatures)
MAX_ATTR_CHARS = 400


class SpanRecord:
    """One span: identity + tree edges + timing.  `t0`/`dur_s` are
    perf-counter seconds relative to the tracer's start."""

    __slots__ = ("span_id", "parent_id", "name", "attrs", "tid",
                 "thread_name", "t0", "dur_s", "_tracer")

    def __init__(self, tracer, span_id, parent_id, name, attrs, tid,
                 thread_name, t0):
        self._tracer = tracer
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self.tid = tid
        self.thread_name = thread_name
        self.t0 = t0
        self.dur_s: Optional[float] = None  # None while open


class _NoopSpan:
    """The shared disarmed span: a no-op context manager.  There is ONE
    instance per process — `span()` disarmed allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """Armed `span()` context manager: push on enter, pop on exit."""

    __slots__ = ("_tracer", "_name", "_attrs", "_record")

    def __init__(self, tracer, name, attrs):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> SpanRecord:
        self._record = self._tracer.push(self._name, self._attrs)
        return self._record

    def __exit__(self, *exc):
        self._tracer.pop(self._record)
        return False


def _json_safe(value):
    if isinstance(value, (bool, int, float)) or value is None:
        return value
    s = str(value)
    return s if len(s) <= MAX_ATTR_CHARS else s[:MAX_ATTR_CHARS] + "..."


class _CompileWatch(logging.Handler):
    """jax_log_compiles records -> `compile` instant events + the
    `jax.retraces` counter.  The handler runs on whatever thread triggered
    the trace, so the compile event lands under the span that caused it —
    per-coordinate retrace attribution falls out of the stack."""

    def __init__(self, tracer: "Tracer"):
        super().__init__()
        self._tracer = tracer

    def emit(self, record):
        try:
            msg = record.getMessage()
            if not msg.startswith("Compiling "):
                return
            self._tracer.retrace_counter.inc()
            self._tracer.event("compile", {"signature": msg})
        except Exception:  # observability must never kill the observed
            pass


class Tracer:
    """One armed tracing session.  Created/installed via
    `telemetry.install()`; all recording methods are thread-safe.

    `proc` is the process's ROLE label ("train", "front", "replica",
    "publisher", ...) — multi-process trace merging (`telemetry.
    distributed`) keys per-process timelines on the (proc, pid) pair the
    run log's leading `meta` record carries."""

    def __init__(self, run_log: Optional[str] = None,
                 watch_compiles: bool = True,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 max_records: int = MAX_RECORDS,
                 proc: Optional[str] = None):
        self.registry = registry or _metrics.default_registry()
        self.retrace_counter = self.registry.counter("jax.retraces")
        self.proc = proc or "proc"
        self.pid = os.getpid()
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._next_id = 1
        self._max_records = max_records
        self.spans: List[SpanRecord] = []      # finished spans
        self.events: List[dict] = []           # instant events
        self.dropped = 0
        self._open_count = 0
        self._finished = False
        self._run_log_path = run_log
        self._run_log = None
        if run_log is not None:
            d = os.path.dirname(os.path.abspath(run_log))
            os.makedirs(d, exist_ok=True)
            # LINE-buffered: a SIGKILLed process's log keeps every record
            # written before the kill (the merge tool and the flight
            # recorder exist precisely for those last seconds — a block-
            # buffered tail would lose them)
            self._run_log = open(run_log, "a", encoding="utf-8",
                                 buffering=1)
            # the merge tool anchors this process's perf-counter timeline
            # (and names its Perfetto process track) from this record
            self._log_record({
                "kind": "meta", "name": "process_meta", "span": None,
                "proc": self.proc, "pid": self.pid,
                "wall0_unix_s": self._wall0})
        self._compile_watch = None
        self._compile_logger = None
        self._prev_log_compiles = None
        self._prev_propagate: Dict[str, bool] = {}
        self._null_handlers: Dict[str, logging.Handler] = {}
        if watch_compiles:
            self._install_compile_watch()

    # -- compile watch -----------------------------------------------------

    #: loggers jax_log_compiles elevates to WARNING; while the watch is
    #: armed their records go to the watch handler only (propagate off),
    #: not to stderr — an armed run must not drown the operator in
    #: "Finished tracing ..." noise
    _COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch",
                        "jax._src.compiler")

    def _install_compile_watch(self) -> None:
        try:
            import jax
        except Exception:
            return
        self._compile_watch = _CompileWatch(self)
        self._prev_propagate = {}
        self._null_handlers = {}
        for name in self._COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            self._prev_propagate[name] = lg.propagate
            lg.propagate = False
            # a handler must be FOUND or logging.lastResort prints the
            # record bare to stderr anyway — NullHandler absorbs it
            self._null_handlers[name] = logging.NullHandler()
            lg.addHandler(self._null_handlers[name])
        self._compile_logger = logging.getLogger(self._COMPILE_LOGGERS[0])
        self._compile_logger.addHandler(self._compile_watch)
        try:
            self._prev_log_compiles = jax.config.jax_log_compiles
            jax.config.update("jax_log_compiles", True)
        except Exception:
            self._prev_log_compiles = None

    def _remove_compile_watch(self) -> None:
        if self._compile_watch is None:
            return
        self._compile_logger.removeHandler(self._compile_watch)
        self._compile_watch = None
        for name, prev in self._prev_propagate.items():
            lg = logging.getLogger(name)
            lg.propagate = prev
            null = self._null_handlers.pop(name, None)
            if null is not None:
                lg.removeHandler(null)
        if self._prev_log_compiles is not None:
            try:
                import jax
                jax.config.update("jax_log_compiles",
                                  self._prev_log_compiles)
            except Exception:
                pass

    # -- span stack --------------------------------------------------------

    def _stack(self) -> List[SpanRecord]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def now(self) -> float:
        return time.perf_counter() - self._t0

    def current_span(self) -> Optional[SpanRecord]:
        stack = self._stack()
        return stack[-1] if stack else None

    def push(self, name: str, attrs: Optional[dict] = None) -> SpanRecord:
        stack = self._stack()
        thread = threading.current_thread()
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
            self._open_count += 1
        record = SpanRecord(
            self, span_id,
            stack[-1].span_id if stack else None,
            name, attrs or {}, thread.ident, thread.name, self.now())
        stack.append(record)
        return record

    def pop(self, record: Optional[SpanRecord]) -> None:
        """Close `record` (and any deeper spans its scope abandoned — an
        exception between push and pop must not corrupt the stack)."""
        if record is None or record.dur_s is not None:
            return
        stack = self._stack()
        if record not in stack:
            # foreign thread / already healed: close it standalone
            self._close(record)
            return
        while stack:
            top = stack.pop()
            self._close(top)
            if top is record:
                return

    def _close(self, record: SpanRecord) -> None:
        record.dur_s = max(self.now() - record.t0, 0.0)
        with self._lock:
            self._open_count -= 1
            if len(self.spans) < self._max_records:
                self.spans.append(record)
            else:
                self.dropped += 1
        line = {
            "kind": "span", "name": record.name, "span": record.span_id,
            "parent": record.parent_id, "tid": record.tid,
            "thread": record.thread_name,
            "t0_s": round(record.t0, 6), "dur_s": round(record.dur_s, 6),
            "attrs": {k: _json_safe(v) for k, v in record.attrs.items()},
        }
        self._log_record(line)
        self._notify_observer("span", line)

    def span(self, name: str, attrs: Optional[dict] = None) -> _Span:
        return _Span(self, name, attrs or {})

    # -- instant events ----------------------------------------------------

    def event(self, name: str, attrs: Optional[dict] = None) -> None:
        current = self.current_span()
        record = {
            "kind": "event", "name": name,
            "span": current.span_id if current is not None else None,
            "tid": threading.current_thread().ident,
            "t_s": round(self.now(), 6),
            "attrs": {k: _json_safe(v) for k, v in (attrs or {}).items()},
        }
        with self._lock:
            if len(self.events) < self._max_records:
                self.events.append(record)
            else:
                self.dropped += 1
        self._log_record(record)
        self._notify_observer("event", record)

    def _notify_observer(self, kind: str, record: dict) -> None:
        obs = _OBSERVER
        if obs is None:
            return
        try:
            obs(kind, record, self)
        except Exception:  # an observer must never kill the traced code
            pass

    # -- run log -----------------------------------------------------------

    def _log_record(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":"))
        # the handle is read AND written under the lock: finish() swaps it
        # to None concurrently with producer threads logging (photonlint
        # PH010 — _run_log is guarded by _lock)
        with self._lock:
            f = self._run_log
            if f is None:
                return
            try:
                f.write(line + "\n")
            except ValueError:  # closed mid-shutdown race: drop, not crash
                pass

    # -- lifecycle ---------------------------------------------------------

    def finish(self) -> None:
        """Close abandoned spans (exception paths), stop the compile
        watch, flush + close the run log.  Idempotent."""
        if self._finished:
            return
        self._finished = True
        self._remove_compile_watch()
        # heal this thread's stack; other threads' open spans are closed
        # from their records at export time (chrome export treats open
        # spans as ending now)
        stack = getattr(self._tls, "stack", None)
        while stack:
            self._close(stack.pop())
        with self._lock:
            if self._run_log is not None:
                try:
                    self._run_log.flush()
                    self._run_log.close()
                finally:
                    self._run_log = None

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"spans": len(self.spans), "events": len(self.events),
                    "open_spans": self._open_count,
                    "dropped": self.dropped,
                    "run_log": self._run_log_path,
                    "proc": self.proc,
                    "wall0_unix_s": self._wall0}


# -- process-global activation (faults.install_plan-style) --------------------

_ACTIVE: Optional[Tracer] = None
_LAST: Optional[Tracer] = None   # kept for export after shutdown

#: one process-global record observer (the flight recorder's tap): called
#: as fn(kind, record_dict, tracer) on every closed span / instant event
#: of whichever tracer is armed.  A plain module global, same disarm
#: discipline as _ACTIVE — the armed hot path pays one None check.
_OBSERVER = None


def set_observer(fn) -> None:
    """Install (or clear, with None) the process-global record observer.
    Last-wins, like install(); telemetry.flight owns the only production
    observer."""
    global _OBSERVER
    _OBSERVER = fn


def active_tracer() -> Optional[Tracer]:
    return _ACTIVE


def last_tracer() -> Optional[Tracer]:
    return _ACTIVE if _ACTIVE is not None else _LAST


def armed() -> bool:
    return _ACTIVE is not None


def install(run_log: Optional[str] = None, watch_compiles: bool = True,
            registry: Optional[_metrics.MetricsRegistry] = None,
            proc: Optional[str] = None) -> Tracer:
    """Arm tracing process-globally; returns the Tracer.  An existing
    tracer is finished and replaced (last-wins, like faults.install_plan)."""
    global _ACTIVE, _LAST
    prev = _ACTIVE
    tracer = Tracer(run_log=run_log, watch_compiles=watch_compiles,
                    registry=registry, proc=proc)
    _ACTIVE = tracer
    if prev is not None:
        prev.finish()
        _LAST = prev
    return tracer


def shutdown() -> Optional[Tracer]:
    """Disarm: finish the active tracer (kept reachable via last_tracer()
    so a trace can still be exported after the run)."""
    global _ACTIVE, _LAST
    tracer, _ACTIVE = _ACTIVE, None
    if tracer is not None:
        tracer.finish()
        _LAST = tracer
    return tracer


class enabled:
    """`with telemetry.enabled() as tracer:` — scoped arming for tests and
    bench legs."""

    def __init__(self, run_log: Optional[str] = None,
                 watch_compiles: bool = True,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 proc: Optional[str] = None):
        self._kw = dict(run_log=run_log, watch_compiles=watch_compiles,
                        registry=registry, proc=proc)

    def __enter__(self) -> Tracer:
        self.tracer = install(**self._kw)
        return self.tracer

    def __exit__(self, *exc):
        if _ACTIVE is self.tracer:
            shutdown()
        else:
            self.tracer.finish()


# -- the hot-path entry points ------------------------------------------------
#
# Each is a module-global None check when disarmed: no allocation beyond
# the **attrs dict the call itself builds (the same cost profile as
# faults.fire(**ctx), which the zero-overhead gates already accept).

def span(name: str, **attrs):
    """Context manager for one span; the shared no-op singleton when
    disarmed."""
    tracer = _ACTIVE
    if tracer is None:
        return NOOP_SPAN
    return tracer.span(name, attrs)


def push(name: str, **attrs) -> Optional[SpanRecord]:
    """Open a span without a `with` block; pair with pop(handle).  None
    when disarmed."""
    tracer = _ACTIVE
    if tracer is None:
        return None
    return tracer.push(name, attrs)


def pop(handle: Optional[SpanRecord]) -> None:
    if handle is not None:
        handle._tracer.pop(handle)


def event(name: str, **attrs) -> None:
    """Instant event attached to the current span; no-op when disarmed."""
    tracer = _ACTIVE
    if tracer is None:
        return
    tracer.event(name, attrs)


def current_span_id() -> Optional[int]:
    tracer = _ACTIVE
    if tracer is None:
        return None
    current = tracer.current_span()
    return current.span_id if current is not None else None


def retrace_count() -> int:
    """Current value of the process-global fresh-trace counter (only
    advances while a tracer's compile watch is armed)."""
    return _metrics.counter("jax.retraces").value
