"""Metrics registry: counters, gauges, bounded-reservoir histograms.

One uniform surface for every quantity this repo used to track through
bespoke bench-only accumulators (PhaseTimings.host_blocked, StreamStats,
TransferStats, ServingMetrics, checkpoint/retry counters): an instrument
is created once by name, incremented from any thread, and read back via
`snapshot()` — which is what `telemetry.snapshot()`, the bench entries,
the cli.train summary, and the serving Prometheus endpoint all render.

Design constraints, in order:

  * cheap writes — an increment is one lock + one int/float add, the same
    cost class as the accumulators it replaces (the TRACER is the part
    with disarm semantics; counters are always live, like StreamStats
    always was);
  * bounded memory — `Histogram` keeps a fixed-size reservoir (a deque
    ring, newest-N) for percentile estimates while `count`/`sum`/`max`/
    `min` stay exact.  Replaces the unbounded percentile lists the naive
    approach grows per request;
  * JSON-safe snapshots — every snapshot value is an int or float, so a
    snapshot can land verbatim in BENCH_*.json / training-summary.json.

Instruments are process-global when created through the module-level
`counter()/gauge()/histogram()` helpers (one registry serves training,
streaming, and checkpointing accounting); components that need isolated
numbers per instance (a ScoringService's metrics, one per service object)
create their own `MetricsRegistry`.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, Optional

from photon_ml_tpu.utils import locktrace

__all__ = ["Counter", "Gauge", "Histogram", "LabeledCounter",
           "MetricsRegistry", "default_registry", "counter", "gauge",
           "histogram"]


class Counter:
    """Monotonically increasing value (int or float increments)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = locktrace.tracked(threading.Lock(), "Counter._lock")
        self._value = 0

    def inc(self, amount=1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r}: negative increment "
                             f"{amount} (use a Gauge for values that fall)")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Last-written value (host-side floats/ints only — never feed a
    device array here; reading one would force a sync)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = locktrace.tracked(threading.Lock(), "Gauge._lock")
        self._value = 0.0

    def set(self, value) -> None:
        with self._lock:
            self._value = value

    def add(self, amount) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Distribution sketch with a BOUNDED reservoir.

    `count`/`sum`/`max`/`min` are exact over every observation; the
    percentile estimates come from the newest-`reservoir` observations (a
    deque ring — the sliding-window behavior ServingMetrics' latency ring
    already had, now shared).  Memory is O(reservoir) forever.
    """

    __slots__ = ("name", "_lock", "_ring", "count", "sum", "max", "min")

    def __init__(self, name: str, reservoir: int = 4096):
        if reservoir < 1:
            raise ValueError(f"histogram {name!r}: reservoir must be >= 1, "
                             f"got {reservoir}")
        self.name = name
        self._lock = locktrace.tracked(threading.Lock(), "Histogram._lock")
        self._ring = collections.deque(maxlen=int(reservoir))
        self.count = 0
        self.sum = 0.0
        self.max: Optional[float] = None
        self.min: Optional[float] = None

    def observe(self, value) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            self._ring.append(v)
            if self.max is None or v > self.max:
                self.max = v
            if self.min is None or v < self.min:
                self.min = v

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile over the reservoir window (None when
        empty).  p in [0, 100]."""
        with self._lock:
            window = sorted(self._ring)
        if not window:
            return None
        rank = min(int(len(window) * p / 100.0), len(window) - 1)
        return window[rank]

    def percentiles(self, ps=(50, 90, 95, 99)) -> Dict[str, Optional[float]]:
        with self._lock:
            window = sorted(self._ring)
        out: Dict[str, Optional[float]] = {}
        for p in ps:
            if not window:
                out[f"p{p:g}"] = None
            else:
                rank = min(int(len(window) * p / 100.0), len(window) - 1)
                out[f"p{p:g}"] = window[rank]
        return out

    @property
    def window(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            window = sorted(self._ring)
            out = {"count": self.count, "sum": self.sum,
                   "max": self.max, "min": self.min,
                   "window": len(window)}
        for p in (50, 90, 95, 99):
            if not window:
                out[f"p{p}"] = None
            else:
                rank = min(int(len(window) * p / 100.0), len(window) - 1)
                out[f"p{p}"] = window[rank]
        return out


class LabeledCounter:
    """A FAMILY of counters distinguished by label values — the fleet
    front's per-(replica, outcome) request accounting.  Children are
    ordinary Counters created on first use of a label combination, so an
    increment costs one dict lookup more than a plain counter; the label
    cardinality is operator-bounded (replica URLs x a small outcome
    enum), never per-request data.

    Prometheus renders each child as `name_total{k="v",...}`; the JSON
    snapshot renders the same children keyed by the canonical
    `k=v,k2=v2` string — one series set on both surfaces, by
    construction."""

    __slots__ = ("name", "label_names", "_lock", "_children")

    def __init__(self, name: str, label_names):
        if not label_names:
            raise ValueError(f"labeled counter {name!r} needs at least "
                             "one label name (use a Counter otherwise)")
        self.name = name
        self.label_names = tuple(label_names)
        self._lock = locktrace.tracked(threading.Lock(),
                                       "LabeledCounter._lock")
        self._children: Dict[tuple, Counter] = {}

    def labels(self, **kv) -> Counter:
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"labeled counter {self.name!r} takes labels "
                f"{list(self.label_names)}, got {sorted(kv)}")
        key = tuple(str(kv[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Counter(self.name)
                self._children[key] = child
            return child

    def inc(self, amount=1, **kv) -> None:
        self.labels(**kv).inc(amount)

    def series(self) -> Dict[tuple, object]:
        """{label-value tuple (in label_names order): value}."""
        with self._lock:
            children = dict(self._children)
        return {key: child.value for key, child in children.items()}

    def snapshot(self) -> Dict[str, object]:
        """{canonical "k=v,k2=v2" string: value} — the JSON surface."""
        return {",".join(f"{n}={v}" for n, v in zip(self.label_names, key)):
                value for key, value in sorted(self.series().items())}


class MetricsRegistry:
    """Named instruments, created on first use; re-asking for a name
    returns the same instrument (asking with a different type raises —
    a counter silently shadowing a gauge would corrupt both)."""

    def __init__(self):
        self._lock = locktrace.tracked(threading.Lock(),
                                       "MetricsRegistry._lock")
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, *args)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, reservoir: int = 4096) -> Histogram:
        return self._get(name, Histogram, reservoir)

    def labeled_counter(self, name: str, label_names) -> LabeledCounter:
        inst = self._get(name, LabeledCounter, tuple(label_names))
        if inst.label_names != tuple(label_names):
            raise TypeError(
                f"labeled counter {name!r} already registered with labels "
                f"{list(inst.label_names)}, requested {list(label_names)}")
        return inst

    def names(self):
        with self._lock:
            return sorted(self._instruments)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """{"counters": {...}, "gauges": {...}, "histograms": {...},
        "labeled": {...}} — every value JSON-safe."""
        with self._lock:
            items = list(self._instruments.items())
        out = {"counters": {}, "gauges": {}, "histograms": {},
               "labeled": {}}
        for name, inst in sorted(items):
            if isinstance(inst, Counter):
                out["counters"][name] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][name] = inst.value
            elif isinstance(inst, LabeledCounter):
                out["labeled"][name] = inst.snapshot()
            else:
                out["histograms"][name] = inst.snapshot()
        return out


# -- process-global default registry ------------------------------------------

_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = MetricsRegistry()
    return _DEFAULT


def counter(name: str) -> Counter:
    return default_registry().counter(name)


def gauge(name: str) -> Gauge:
    return default_registry().gauge(name)


def histogram(name: str, reservoir: int = 4096) -> Histogram:
    return default_registry().histogram(name, reservoir)
