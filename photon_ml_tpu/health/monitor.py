"""HealthMonitor: continuous model-quality gates on the serving path.

The live counterpart of the offline diagnostics tier (`diagnostics/`):
where `cli.diagnose` judges a model once against a held-out set, this
monitor judges the SERVING model continuously against its own traffic —
and acts on the verdict.  Four signal families, two window clocks:

  * score-distribution drift (every scored row, `window_scores` per
    window): PSI + binned KS against a baseline histogram snapshotted at
    each `ModelRegistry.install()` — reset on full-model swap, carried
    across row-level delta publishes (drift.py).
  * streaming calibration (every feedback-joined label, `window_labels`
    per window): Hosmer–Lemeshow chi^2 over probability deciles, the
    same per-bin algebra as `diagnostics/hl.py` (calibration.py).
  * sliding-window loss + AUC on the same labeled rows (host numpy f64,
    `evaluation.area_under_roc_curve` as the AUC).
  * online-update vitals from the OnlineUpdater: per-coordinate delta
    magnitudes (L2 of published row - prior) and the freeze rate.

Each closed window updates its gates (`HealthConfig.thresholds()`); a
gate that breaches `sustain_windows` consecutive windows TRIPS: /healthz
flips to degraded, the OnlineUpdater pauses (`pause_updates`), and gates
named in `rollback_on` trigger the registry's delta-aware rollback.
`recovery_windows` consecutive clean windows recover: updates resume,
status returns to ok.

Hot-path discipline: the scoring thread pays one lock + a `searchsorted`
/ `bincount` pair per BATCH (never per row, never a device op, zero
fresh XLA traces); with no monitor constructed the service's hook is a
plain None check — the same disarm shape as `faults.fire()`.  Window
EVALUATION (chi^2 CDF, PSI, AUC) runs on whichever thread closed the
window, OUTSIDE the monitor lock, on an O(bins)/O(window) snapshot; the
`health.evaluate` fault site makes the evaluation path chaos-testable.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import flight
from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve
from photon_ml_tpu.health.calibration import StreamingCalibration
from photon_ml_tpu.health.config import GATE_NAMES, HealthConfig
from photon_ml_tpu.health.drift import DriftDetector
from photon_ml_tpu.utils import faults, locktrace

logger = logging.getLogger("photon_ml_tpu")


def _np_sigmoid(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.tanh(0.5 * z))


#: task -> host-numpy inverse link producing a PROBABILITY (calibration
#: is only defined where the mean is one); margins stay the drift signal
#: for every task.
INVERSE_LINKS = {"logistic_regression": _np_sigmoid}

#: task -> host-numpy per-row loss on (margin+offset, label).  Host numpy
#: keeps window evaluation off the device entirely: no dispatches, no
#: shape-keyed eager kernels, zero fresh traces with health armed.
NP_LOSSES = {
    "logistic_regression": lambda z, y: np.logaddexp(0.0, z) - y * z,
    "linear_regression": lambda z, y: 0.5 * (z - y) ** 2,
    "poisson_regression": lambda z, y: np.exp(z) - y * z,
}


class GateState:
    """One gate's consecutive-window bookkeeping."""

    __slots__ = ("threshold", "value", "breaches", "clean", "tripped",
                 "windows", "trips")

    def __init__(self, threshold: Optional[float]):
        self.threshold = threshold
        self.value: Optional[float] = None
        self.breaches = 0        # consecutive breached windows
        self.clean = 0           # consecutive clean windows
        self.tripped = False
        self.windows = 0         # windows this gate evaluated
        self.trips = 0           # lifetime trip count

    def to_dict(self) -> dict:
        return {"threshold": self.threshold, "value": self.value,
                "breaches": self.breaches, "tripped": self.tripped,
                "windows": self.windows, "trips": self.trips}


class HealthMonitor:
    """Streaming calibration + drift + online-update vitals -> gates.

    Constructed by `ScoringService(health=HealthConfig())`; standalone
    construction (tests, replay tooling) needs only a config — `metrics`,
    `bind()` and the swap hook are optional wiring.
    """

    def __init__(self, config: HealthConfig, metrics=None,
                 task_type: Optional[str] = None):
        self.config = config
        self.metrics = metrics            # ServingMetrics (or None)
        self._lock = locktrace.tracked(threading.Lock(),
                                       "HealthMonitor._lock")
        # action targets, wired by bind(); read under the lock
        self._registry = None                                 # photonlint: guarded-by=_lock
        self._updater = None                                  # photonlint: guarded-by=_lock
        self._task = task_type                                # photonlint: guarded-by=_lock
        # -- drift state (scoring path) --------------------------------
        self._drift = DriftDetector(config.drift_bins,
                                    config.baseline_scores)   # photonlint: guarded-by=_lock
        # -- label-window state (feedback path) ------------------------
        self._cal = StreamingCalibration(config.calibration_bins)  # photonlint: guarded-by=_lock
        w = config.window_labels
        self._margins = np.empty(w)                           # photonlint: guarded-by=_lock
        self._labels = np.empty(w)                            # photonlint: guarded-by=_lock
        self._weights = np.empty(w)                           # photonlint: guarded-by=_lock
        self._label_n = 0                                     # photonlint: guarded-by=_lock
        self._loss_sum = 0.0                                  # photonlint: guarded-by=_lock
        self._loss_wsum = 0.0                                 # photonlint: guarded-by=_lock
        # -- online-update vitals (updater thread) ---------------------
        self._delta_sum = 0.0                                 # photonlint: guarded-by=_lock
        self._delta_max = 0.0                                 # photonlint: guarded-by=_lock
        self._delta_n = 0                                     # photonlint: guarded-by=_lock
        self._delta_by_coord: Dict[str, float] = {}           # photonlint: guarded-by=_lock
        self._freezes = 0                                     # photonlint: guarded-by=_lock
        # -- gates -----------------------------------------------------
        self._gates = {name: GateState(t)
                       for name, t in config.thresholds().items()}  # photonlint: guarded-by=_lock
        self._degraded = False                                # photonlint: guarded-by=_lock
        self._we_paused = False                               # photonlint: guarded-by=_lock
        self._windows = 0                                     # photonlint: guarded-by=_lock
        self._skipped = 0                                     # photonlint: guarded-by=_lock
        self._rollbacks = 0                                   # photonlint: guarded-by=_lock
        self.version: Optional[str] = None                    # photonlint: guarded-by=_lock

    # -- wiring -------------------------------------------------------------

    def bind(self, registry=None, updater=None,
             task_type: Optional[str] = None) -> None:
        """Attach the action targets (pause/resume on the updater, the
        delta-aware rollback on the registry)."""
        with self._lock:
            if registry is not None:
                self._registry = registry
            if updater is not None:
                self._updater = updater
            if task_type is not None:
                self._task = task_type

    def on_model_event(self, version: str, action: str) -> None:
        """ModelRegistry swap hook: a new full model is live.  The drift
        baseline, open windows, and every gate's breach history belong to
        the OUTGOING model — reset everything and (if the PAUSE was ours)
        let the updater run against the fresh version."""
        with self._lock:
            self.version = version
            self._drift.reset_baseline()
            self._cal.reset()
            self._label_n = 0
            self._loss_sum = self._loss_wsum = 0.0
            self._delta_sum = self._delta_max = 0.0
            self._delta_n = 0
            self._delta_by_coord = {}
            self._freezes = 0
            for g in self._gates.values():
                g.value = None
                g.breaches = g.clean = 0
                g.tripped = False
            was_degraded, self._degraded = self._degraded, False
            resume, self._we_paused = self._we_paused, False
            updater = self._updater
        if was_degraded:
            telemetry.event("health_reset", version=str(version),
                            action=action)
        if resume and updater is not None:
            updater.resume()
        self._publish_status()

    # -- observation: the scoring path --------------------------------------

    def observe_scores(self, scores: np.ndarray) -> None:
        """Every served batch's margins (called by the service's batch
        worker — one lock + histogram add per batch)."""
        s = np.asarray(scores, np.float64)
        closed: List[dict] = []
        with self._lock:
            lo = 0
            while lo < len(s):
                room = self.config.window_scores - self._drift.window_count
                hi = min(len(s), lo + max(room, 1))
                self._drift.observe(s[lo:hi])
                lo = hi
                if self._drift.window_count >= self.config.window_scores:
                    win = self._drift.take()
                    if win is not None:
                        closed.append({"kind": "drift", "window": win})
        for snap in closed:
            self._evaluate(snap)

    # -- observation: the feedback path --------------------------------------

    def observe_feedback(self, scorer, features, ids, labels,
                         weights=None, offsets=None) -> None:
        """A feedback batch joined back to the live model: score it once
        through the warmed bucket programs, fold offsets, and accumulate
        calibration/loss/AUC windows.  Called on the feedback request
        thread (off the scoring hot path)."""
        labels = np.asarray(labels, np.float64)
        n = len(labels)
        w = (np.ones(n) if weights is None
             else np.asarray(weights, np.float64))
        off = (np.zeros(n) if offsets is None
               else np.asarray(offsets, np.float64))
        margins = scorer.score(features, ids).scores + off
        with self._lock:
            task = self._task
        task = task or scorer.model.task_type
        inv = INVERSE_LINKS.get(task)
        loss_fn = NP_LOSSES.get(task)
        probs = inv(margins) if inv is not None else None
        losses = loss_fn(margins, labels) if loss_fn is not None else None
        closed: List[dict] = []
        with self._lock:
            lo = 0
            while lo < n:
                room = self.config.window_labels - self._label_n
                hi = min(n, lo + room)
                k = hi - lo
                self._margins[self._label_n:self._label_n + k] = margins[lo:hi]
                self._labels[self._label_n:self._label_n + k] = labels[lo:hi]
                self._weights[self._label_n:self._label_n + k] = w[lo:hi]
                self._label_n += k
                if probs is not None:
                    self._cal.update(probs[lo:hi], labels[lo:hi])
                if losses is not None:
                    self._loss_sum += float(np.sum(w[lo:hi] * losses[lo:hi]))
                    self._loss_wsum += float(np.sum(w[lo:hi]))
                lo = hi
                if self._label_n >= self.config.window_labels:
                    closed.append(self._take_label_window_locked())
        for snap in closed:
            self._evaluate(snap)

    def _take_label_window_locked(self) -> dict:
        """Snapshot + reset the label-window accumulators (lock held)."""
        k = self._label_n
        snap = {
            "kind": "labels",
            "rows": k,
            "calibration": self._cal.take(),
            "margins": self._margins[:k].copy(),
            "labels": self._labels[:k].copy(),
            "weights": self._weights[:k].copy(),
            "loss": (self._loss_sum / self._loss_wsum
                     if self._loss_wsum > 0 else None),
            "delta_l2_mean": (self._delta_sum / self._delta_n
                              if self._delta_n else None),
            "delta_l2_max": self._delta_max if self._delta_n else None,
            "delta_by_coordinate": dict(self._delta_by_coord),
            "freezes": self._freezes,
        }
        self._label_n = 0
        self._loss_sum = self._loss_wsum = 0.0
        self._delta_sum = self._delta_max = 0.0
        self._delta_n = 0
        self._delta_by_coord = {}
        self._freezes = 0
        return snap

    # -- observation: the online updater -------------------------------------

    def observe_published(self, coordinate: str,
                          magnitudes: np.ndarray) -> None:
        """Per-row L2 of (published value - prior) for one delta."""
        m = np.asarray(magnitudes, np.float64)
        if not len(m):
            return
        mx = float(np.max(m))
        with self._lock:
            self._delta_sum += float(np.sum(m))
            self._delta_n += len(m)
            self._delta_max = max(self._delta_max, mx)
            prev = self._delta_by_coord.get(coordinate, 0.0)
            self._delta_by_coord[coordinate] = max(prev, mx)

    def observe_freeze(self, coordinate: str) -> None:
        with self._lock:
            self._freezes += 1

    # -- evaluation -----------------------------------------------------------

    def _evaluate(self, snap: dict) -> None:
        """One closed window -> gate values -> transitions -> actions.
        Runs OUTSIDE the monitor lock on a private snapshot."""
        kind = snap["kind"]
        try:
            faults.fire("health.evaluate", kind=kind)
        except BaseException as e:
            if faults.is_transient(e):
                with self._lock:
                    self._skipped += 1
                if self.metrics is not None:
                    self.metrics.observe_health_skipped()
                telemetry.event("health_evaluate_skipped", kind=kind,
                                error=f"{type(e).__name__}: {e}")
                return
            raise
        with telemetry.span("health_evaluate", kind=kind):
            if kind == "drift":
                results = self._drift_results(snap)
            else:
                results = self._label_results(snap)
            outcome = self._apply_window(kind, results)
        self._publish_window(kind, snap, results, outcome)
        self._act(outcome)

    def _drift_results(self, snap) -> Dict[str, tuple]:
        win = snap["window"]
        c = self.config
        return {
            "drift_psi": (win.psi, c.psi_max is not None
                          and win.psi > c.psi_max),
            "drift_ks": (win.ks, c.ks_max is not None and win.ks > c.ks_max),
        }

    def _label_results(self, snap) -> Dict[str, tuple]:
        c = self.config
        results: Dict[str, tuple] = {}
        cal = snap["calibration"]
        if cal is not None:
            results["calibration"] = (
                cal.p_value, c.calibration_p_min is not None
                and cal.p_value < c.calibration_p_min)
            snap["hl_chi2"] = cal.chi_squared
        auc = area_under_roc_curve(snap["margins"], snap["labels"],
                                   snap["weights"])
        if np.isfinite(auc):
            results["auc"] = (float(auc),
                              c.auc_min is not None and auc < c.auc_min)
            snap["auc"] = float(auc)
        if snap["loss"] is not None:
            results["loss"] = (snap["loss"], c.loss_max is not None
                               and snap["loss"] > c.loss_max)
        if snap["delta_l2_max"] is not None:
            results["delta_l2"] = (
                snap["delta_l2_max"], c.delta_l2_max is not None
                and snap["delta_l2_max"] > c.delta_l2_max)
        results["freeze_rate"] = (
            float(snap["freezes"]), c.freeze_max is not None
            and snap["freezes"] > c.freeze_max)
        return results

    def _apply_window(self, kind: str,
                      results: Dict[str, tuple]) -> dict:
        """Fold one window's gate values into the consecutive-breach
        state machine (brief lock) and return the transition outcome."""
        c = self.config
        tripped: List[str] = []
        recovered: List[str] = []
        breaches = 0
        with self._lock:
            for name, (value, breach) in results.items():
                g = self._gates[name]
                g.value = value
                g.windows += 1
                if breach:
                    breaches += 1
                    g.breaches += 1
                    g.clean = 0
                    if not g.tripped and g.breaches >= c.sustain_windows:
                        g.tripped = True
                        g.trips += 1
                        tripped.append((name, value, g.threshold))
                else:
                    g.clean += 1
                    g.breaches = 0
                    if g.tripped and g.clean >= c.recovery_windows:
                        g.tripped = False
                        recovered.append(name)
            was_degraded = self._degraded
            self._degraded = any(g.tripped for g in self._gates.values())
            now_degraded = self._degraded
            self._windows += 1
            pause = (tripped and c.pause_updates and not self._we_paused
                     and self._updater is not None)
            if pause:
                self._we_paused = True
            resume = (was_degraded and not now_degraded and self._we_paused)
            if resume:
                self._we_paused = False
            rollback = [n for n, _v, _t in tripped if n in c.rollback_on]
            updater = self._updater
            registry = self._registry
        return {"tripped": tripped, "recovered": recovered,
                "breaches": breaches, "degraded": now_degraded,
                "was_degraded": was_degraded, "pause": bool(pause),
                "resume": bool(resume), "rollback": rollback,
                "updater": updater, "registry": registry}

    def _act(self, outcome: dict) -> None:
        """Execute the transitions decided by `_apply_window` — pause /
        resume / delta-aware rollback — outside every monitor lock."""
        updater, registry = outcome["updater"], outcome["registry"]
        for name, value, threshold in outcome["tripped"]:
            telemetry.event("health_gate_tripped", gate=name, value=value)
            logger.warning("health gate %r TRIPPED (value=%s threshold=%s)",
                           name, value, threshold)
            if self.metrics is not None:
                self.metrics.observe_health_trip()
        if outcome["tripped"]:
            # the flight ring holds the windows that led to the trip —
            # dump BEFORE acting (pause/rollback mutate the state the
            # postmortem needs to see)
            flight.trigger("health.gate_trip",
                           gates=",".join(n for n, _v, _t
                                          in outcome["tripped"]))
        for name in outcome["recovered"]:
            telemetry.event("health_gate_recovered", gate=name)
            logger.info("health gate %r recovered", name)
            if self.metrics is not None:
                self.metrics.observe_health_recovery()
        if outcome["pause"] and updater is not None:
            gates = ",".join(n for n, _v, _t in outcome["tripped"])
            updater.pause(reason=f"health: {gates}")
            telemetry.event("health_updates_paused", gates=gates)
        if outcome["rollback"] and registry is not None:
            if registry.pending_deltas() > 0:
                registry.rollback()
                with self._lock:
                    self._rollbacks += 1
                if self.metrics is not None:
                    self.metrics.observe_health_rollback()
                telemetry.event("health_rollback",
                                gates=",".join(outcome["rollback"]))
                logger.warning("health gates %s triggered delta-aware "
                               "rollback", outcome["rollback"])
            else:
                telemetry.event("health_rollback_skipped",
                                reason="no pending deltas")
        if outcome["resume"] and updater is not None:
            updater.resume()
            telemetry.event("health_updates_resumed")
        self._publish_status()

    def _publish_window(self, kind, snap, results, outcome) -> None:
        if self.metrics is None:
            return
        values = {name: v for name, (v, _b) in results.items()}
        if kind == "drift":
            self.metrics.observe_health_score_window(
                rows=snap["window"].count, psi=values.get("drift_psi"),
                ks=values.get("drift_ks"), breaches=outcome["breaches"])
        else:
            self.metrics.observe_health_label_window(
                rows=snap["rows"], hl_chi2=snap.get("hl_chi2"),
                hl_p=values.get("calibration"), auc=values.get("auc"),
                loss=values.get("loss"),
                delta_l2_mean=snap["delta_l2_mean"],
                delta_l2_max=snap["delta_l2_max"],
                freezes=snap["freezes"], breaches=outcome["breaches"])

    def _publish_status(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            degraded = self._degraded
            paused = self._we_paused
            ready = self._drift.baseline_ready
        self.metrics.observe_health_status(
            degraded=degraded, paused=paused, baseline_ready=ready)

    # -- introspection --------------------------------------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def verdict(self) -> dict:
        """The health verdict the /healthz endpoint embeds: overall status
        plus per-gate detail."""
        with self._lock:
            gates = {name: self._gates[name].to_dict()
                     for name in GATE_NAMES}
            return {
                "status": "degraded" if self._degraded else "ok",
                "model_version": self.version,
                "baseline_ready": self._drift.baseline_ready,
                "windows_evaluated": self._windows,
                "windows_skipped": self._skipped,
                "rollbacks": self._rollbacks,
                "updates_paused_by_health": self._we_paused,
                "delta_l2_by_coordinate": dict(self._delta_by_coord),
                "gates": gates,
            }
