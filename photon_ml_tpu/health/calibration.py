"""Streaming Hosmer–Lemeshow calibration over fixed probability bins.

The offline diagnostics tier (`diagnostics/hl.py`) computes the HL test in
one batch pass with a data-dependent bin count; a serving process sees its
labels as a stream and cannot hold them.  This accumulator keeps ONLY the
four per-bin sums the chi^2 needs — expected/observed positives and
negatives — so memory is O(bins) forever and an update is a digitize +
four bincounts on the incoming batch (no per-row Python).

The bin rule is hl.py's, with the bin COUNT fixed up front (score deciles
by default) instead of derived from n: equal-width probability edges over
[0, 1], `digitize` against the interior edges, and the identical per-bin
chi^2 contribution `(obs-exp)^2/exp` for positives and negatives with
zero-expectation bins skipped.  Feeding the same (p, y) traffic through
this accumulator and through `hosmer_lemeshow` (with a dimension count
that yields the same bin count) produces the same chi^2 / p-value up to
float summation order — the tier-1 parity test holds them to 1e-12.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np
from scipy.stats import chi2 as _chi2


@dataclasses.dataclass
class CalibrationWindow:
    """One closed window's HL verdict + the per-bin evidence."""

    count: int
    chi_squared: float
    degrees_of_freedom: int
    prob_at_chi_square: float      # CDF(chi2) — near 1 = poor calibration
    expected_pos: List[float]
    expected_neg: List[float]
    observed_pos: List[float]
    observed_neg: List[float]

    @property
    def p_value(self) -> float:
        return 1.0 - self.prob_at_chi_square

    def to_dict(self) -> dict:
        return {"count": self.count, "chi_squared": self.chi_squared,
                "degrees_of_freedom": self.degrees_of_freedom,
                "prob_at_chi_square": self.prob_at_chi_square,
                "p_value": self.p_value}


class StreamingCalibration:
    """O(bins) streaming accumulator for the HL calibration statistic.

    NOT thread-safe: the HealthMonitor serializes updates under its own
    lock (one lock for the whole health state, not one per accumulator).
    Weights are deliberately ignored — `diagnostics/hl.py` defines the
    unweighted test and is this accumulator's parity oracle.
    """

    def __init__(self, bins: int = 10):
        if bins < 3:
            raise ValueError(f"calibration needs >= 3 bins for a chi^2 "
                             f"with >= 1 dof, got {bins}")
        self.bins = int(bins)
        self.edges = np.linspace(0.0, 1.0, self.bins + 1)
        self._exp_pos = np.zeros(self.bins)
        self._exp_neg = np.zeros(self.bins)
        self._obs_pos = np.zeros(self.bins)
        self._obs_neg = np.zeros(self.bins)
        self.count = 0

    def update(self, probs: np.ndarray, labels: np.ndarray) -> None:
        """Accumulate a batch of (predicted probability, binary label)."""
        p = np.asarray(probs, np.float64)
        y = np.asarray(labels, np.float64) > 0.5
        which = np.clip(np.digitize(p, self.edges[1:-1]), 0, self.bins - 1)
        self._exp_pos += np.bincount(which, weights=p, minlength=self.bins)
        self._exp_neg += np.bincount(which, weights=1.0 - p,
                                     minlength=self.bins)
        self._obs_pos += np.bincount(which, weights=y.astype(np.float64),
                                     minlength=self.bins)
        self._obs_neg += np.bincount(which, weights=(~y).astype(np.float64),
                                     minlength=self.bins)
        self.count += len(p)

    def report(self) -> Optional[CalibrationWindow]:
        """The HL verdict over everything accumulated so far (None when
        empty).  Same per-bin algebra as `diagnostics.hl.hosmer_lemeshow`:
        chi^2 terms skipped where the expectation is zero, dof = bins - 2
        floored at 1."""
        if self.count == 0:
            return None
        chi2_score = 0.0
        for exp, obs in ((self._exp_pos, self._obs_pos),
                         (self._exp_neg, self._obs_neg)):
            nz = exp > 0
            chi2_score += float(np.sum((obs[nz] - exp[nz]) ** 2 / exp[nz]))
        dof = max(1, self.bins - 2)
        return CalibrationWindow(
            count=self.count, chi_squared=chi2_score,
            degrees_of_freedom=dof,
            prob_at_chi_square=float(_chi2(dof).cdf(chi2_score)),
            expected_pos=self._exp_pos.tolist(),
            expected_neg=self._exp_neg.tolist(),
            observed_pos=self._obs_pos.tolist(),
            observed_neg=self._obs_neg.tolist())

    def take(self) -> Optional[CalibrationWindow]:
        """Close the window: report + reset the accumulators."""
        out = self.report()
        self.reset()
        return out

    def reset(self) -> None:
        self._exp_pos[:] = 0.0
        self._exp_neg[:] = 0.0
        self._obs_pos[:] = 0.0
        self._obs_neg[:] = 0.0
        self.count = 0
