"""Score-distribution drift: PSI + binned KS against an install baseline.

The baseline is a reservoir of the first `baseline_size` margins the live
model produces after `ModelRegistry.install()` — reset on every full-model
swap, CARRIED across row-level delta publishes (a delta is the same model
version refining itself; resetting there would blind the detector to
exactly the degradation the online tier can cause).  Once the reservoir
fills, its empirical quantiles become the bin edges (equal-mass bins make
PSI well-conditioned: no empty baseline bins by construction), and every
subsequent score costs one `searchsorted` lane + a bincount add.

Two statistics per closed window, both from the same histogram:

  * PSI — sum over bins of (cur - base) * ln(cur / base) with fractions
    floored at `_EPS` (the standard smoothing; an empty current bin must
    not produce an infinite index).  Industry folklore: < 0.1 stable,
    0.1-0.25 drifting, > 0.25 act.
  * KS — max |CDF_cur - CDF_base| evaluated at the bin boundaries (the
    binned sup-statistic; with equal-mass baseline bins the resolution is
    1/bins, which is exactly the granularity the gate thresholds speak).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

_EPS = 1e-4


@dataclasses.dataclass
class DriftWindow:
    """One closed drift window's statistics."""

    count: int
    psi: float
    ks: float
    fractions: list          # current-window per-bin fractions

    def to_dict(self) -> dict:
        return {"count": self.count, "psi": self.psi, "ks": self.ks}


class DriftDetector:
    """Baseline-relative score-distribution drift (PSI + binned KS).

    NOT thread-safe: the HealthMonitor serializes access under its lock.
    """

    def __init__(self, bins: int = 10, baseline_size: int = 2048):
        if bins < 2:
            raise ValueError(f"drift needs >= 2 bins, got {bins}")
        self.bins = int(bins)
        self.baseline_size = int(baseline_size)
        self._base_buf = np.empty(self.baseline_size)
        self._base_n = 0
        self._edges: Optional[np.ndarray] = None   # interior edges [bins-1]
        self._base_frac: Optional[np.ndarray] = None
        self._hist = np.zeros(self.bins, np.int64)
        self.window_count = 0

    @property
    def baseline_ready(self) -> bool:
        return self._edges is not None

    def reset_baseline(self) -> None:
        """Forget everything: a new model version is live (full swap)."""
        self._base_n = 0
        self._edges = None
        self._base_frac = None
        self._hist[:] = 0
        self.window_count = 0

    def _finalize_baseline(self) -> None:
        sample = self._base_buf[:self._base_n]
        qs = np.linspace(0.0, 1.0, self.bins + 1)[1:-1]
        self._edges = np.quantile(sample, qs)
        counts = np.bincount(
            np.searchsorted(self._edges, sample, side="right"),
            minlength=self.bins).astype(np.float64)
        self._base_frac = counts / counts.sum()

    def observe(self, scores: np.ndarray) -> int:
        """Accumulate a batch of raw margins.  Returns how many landed in
        the CURRENT window (rows consumed by baseline collection don't
        count toward window geometry)."""
        s = np.asarray(scores, np.float64)
        if self._edges is None:
            take = min(len(s), self.baseline_size - self._base_n)
            if take:
                self._base_buf[self._base_n:self._base_n + take] = s[:take]
                self._base_n += take
            if self._base_n >= self.baseline_size:
                self._finalize_baseline()
            s = s[take:]
            if not len(s):
                return 0
        self._hist += np.bincount(
            np.searchsorted(self._edges, s, side="right"),
            minlength=self.bins)
        self.window_count += len(s)
        return len(s)

    def take(self) -> Optional[DriftWindow]:
        """Close the current window: compute PSI/KS vs the baseline and
        reset the histogram (None when the baseline is not ready or the
        window is empty)."""
        if self._edges is None or self.window_count == 0:
            return None
        total = float(self._hist.sum())
        cur = self._hist / total
        b = np.maximum(self._base_frac, _EPS)
        c = np.maximum(cur, _EPS)
        psi = float(np.sum((c - b) * np.log(c / b)))
        ks = float(np.max(np.abs(np.cumsum(cur) - np.cumsum(self._base_frac))))
        out = DriftWindow(count=int(total), psi=psi, ks=ks,
                          fractions=cur.tolist())
        self._hist[:] = 0
        self.window_count = 0
        return out
