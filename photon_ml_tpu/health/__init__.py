"""Live model health: streaming calibration, drift detection, and
health-gated online updates.

The online tier (photon_ml_tpu/online/) rewrites the live model from its
own traffic; this package watches whether those continuous updates — or
the traffic itself — are degrading the model, and GATES the update loop
on the verdict:

  - `calibration.StreamingCalibration` — O(bins) streaming Hosmer–
    Lemeshow over probability deciles, same per-bin algebra as the
    offline `diagnostics/hl.py` (which stays the parity oracle).
  - `drift.DriftDetector` — score-distribution PSI + binned KS against a
    baseline histogram snapshotted at each `ModelRegistry.install()`
    (reset on swap, carried across deltas).
  - `monitor.HealthMonitor` — window clocks, sliding loss/AUC, delta-
    magnitude/freeze-rate vitals, and the gate state machine: sustained
    breaches flip /healthz to degraded, pause the OnlineUpdater, and
    (per config) trigger the delta-aware rollback; sustained recovery
    resumes updates.
  - `config.HealthConfig` — thresholds + window geometry
    (`cli.serve --health-config`).

Wire-up: `ScoringService(..., health=HealthConfig())`; metrics ride the
serving Prometheus text + JSON surfaces as the `health.*` family, and
every window evaluation is a telemetry span with trip/recovery events.
"""
from photon_ml_tpu.health.calibration import (  # noqa: F401
    CalibrationWindow, StreamingCalibration,
)
from photon_ml_tpu.health.config import GATE_NAMES, HealthConfig  # noqa: F401
from photon_ml_tpu.health.drift import DriftDetector, DriftWindow  # noqa: F401
from photon_ml_tpu.health.monitor import HealthMonitor  # noqa: F401
