"""HealthConfig: the knobs of the live model-health layer.

Every threshold is Optional — None disables that gate — so an operator can
run pure drift monitoring (no labels needed), pure calibration monitoring,
or the full set.  Windows are COUNT-based (labeled rows / scored rows),
never wall-clock, so detection latency is deterministic under replay and
the bench can gate "tripped within <= 3 evaluation windows" exactly.

`cli.serve --health-config` takes this as inline JSON or `@file`
(`from_dict` rejects unknown keys loudly — a typo'd threshold must not
silently disarm a gate).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: every gate the monitor can evaluate, in report order
GATE_NAMES = ("calibration", "drift_psi", "drift_ks", "auc", "loss",
              "delta_l2", "freeze_rate")


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Model-health gates + window geometry (cli.serve --health-config)."""

    # -- window geometry ----------------------------------------------------
    window_labels: int = 256      # labeled rows per calibration/loss window
    window_scores: int = 4096     # scored rows per drift window
    baseline_scores: int = 2048   # baseline reservoir collected per install
    calibration_bins: int = 10    # probability deciles (hl.py formula)
    drift_bins: int = 10          # baseline-quantile score bins
    sustain_windows: int = 2      # consecutive breaches that trip a gate
    recovery_windows: int = 2     # consecutive clean windows that recover

    # -- gate thresholds (None = gate disabled) -----------------------------
    calibration_p_min: Optional[float] = 1e-3  # HL p-value floor
    psi_max: Optional[float] = 0.25            # population stability index
    ks_max: Optional[float] = 0.2              # binned KS statistic
    auc_min: Optional[float] = None            # window AUC floor
    loss_max: Optional[float] = None           # window mean-loss ceiling
    delta_l2_max: Optional[float] = None       # max per-row delta L2/window
    freeze_max: Optional[int] = None           # frozen entities per window

    # -- actions on a tripped gate ------------------------------------------
    pause_updates: bool = True                 # pause the OnlineUpdater
    rollback_on: Tuple[str, ...] = ()          # gates that also trigger the
    #                                            delta-aware rollback

    def __post_init__(self):
        for name in ("window_labels", "window_scores", "baseline_scores",
                     "calibration_bins", "drift_bins", "sustain_windows",
                     "recovery_windows"):
            if int(getattr(self, name)) < 1:
                raise ValueError(f"HealthConfig.{name} must be >= 1")
        object.__setattr__(self, "rollback_on", tuple(self.rollback_on))
        unknown = set(self.rollback_on) - set(GATE_NAMES)
        if unknown:
            raise ValueError(
                f"HealthConfig.rollback_on names unknown gate(s) "
                f"{sorted(unknown)} (gates: {list(GATE_NAMES)})")

    @classmethod
    def from_dict(cls, d: dict) -> "HealthConfig":
        if not isinstance(d, dict):
            raise ValueError("health config must be a JSON object")
        allowed = {f.name for f in dataclasses.fields(cls)}
        bad = set(d) - allowed
        if bad:
            raise ValueError(f"health config: unknown key(s) {sorted(bad)} "
                             f"(allowed: {sorted(allowed)})")
        return cls(**d)

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["rollback_on"] = list(self.rollback_on)
        return out

    def thresholds(self) -> dict:
        """gate name -> threshold (None = disabled), in GATE_NAMES order."""
        return {
            "calibration": self.calibration_p_min,
            "drift_psi": self.psi_max,
            "drift_ks": self.ks_max,
            "auc": self.auc_min,
            "loss": self.loss_max,
            "delta_l2": self.delta_l2_max,
            "freeze_rate": (None if self.freeze_max is None
                            else float(self.freeze_max)),
        }
