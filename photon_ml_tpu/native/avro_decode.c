/* Native Avro block decoder: schema-compiled op programs over raw blocks.
 *
 * Role of the reference's AvroDataReader hot path (photon-client/.../data/
 * avro/AvroDataReader.scala:53-451): bulk ingest of TrainingExampleAvro /
 * BayesianLinearModelAvro / ScoringResultAvro container files.  The Python
 * side (photon_ml_tpu/data/avro_native.py) compiles a record schema into a
 * flat int32 op program; this interpreter executes it once per record over
 * a decompressed container block, appending leaf values into growable typed
 * columns.  One C loop replaces the per-record pure-Python decode — the
 * reference leans on Spark executors + the JVM Avro runtime for the same
 * bulk-decode role.
 *
 * Supported shapes (everything the photon schemas need):
 *   primitives long/int/double/float/boolean/string/bytes/enum,
 *   record, array<...>, union [null, X] (either order), map (skipped).
 * Anything else is rejected at compile time in Python and falls back to the
 * pure-Python codec.
 *
 * Build: cc -O3 -shared -fPIC avro_decode.c -o libavrodec.so
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

enum {
    OP_LONG = 0,    /* col */
    OP_DOUBLE = 1,  /* col */
    OP_FLOAT = 2,   /* col */
    OP_BOOL = 3,    /* col */
    OP_STRING = 4,  /* col (also bytes) */
    OP_ENUM = 5,    /* col */
    OP_OPT = 6,     /* null_branch_index, present_col, body_len, body... */
    OP_ARRAY = 7,   /* count_col, body_len, body... */
    OP_MAP_SKIP = 8,/* (no args) skip map<string, string-or-bytes-like> */
    OP_MAP = 9      /* count_col, key_col, val_col: map<string, string> */
};

enum { KIND_I64 = 0, KIND_F64 = 1, KIND_STR = 2 };

typedef struct {
    int32_t kind;
    int64_t len, cap;      /* elements */
    int64_t blen, bcap;    /* string blob bytes */
    int64_t *i64;          /* KIND_I64 data, or KIND_STR end offsets */
    double *f64;           /* KIND_F64 data */
    uint8_t *blob;         /* KIND_STR bytes */
} Col;

typedef struct {
    const uint8_t *p, *end;
    int err;
} Cur;

static int64_t read_varlong(Cur *c) {
    uint64_t acc = 0;
    int shift = 0;
    while (1) {
        if (c->p >= c->end || shift > 63) { c->err = 1; return 0; }
        uint8_t b = *c->p++;
        acc |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    return (int64_t)(acc >> 1) ^ -(int64_t)(acc & 1); /* zigzag */
}

static int ensure_cap(Col *col, int64_t extra) {
    if (col->len + extra > col->cap) {
        int64_t nc = col->cap ? col->cap * 2 : 1024;
        while (nc < col->len + extra) nc *= 2;
        if (col->kind == KIND_F64) {
            double *nf = realloc(col->f64, nc * sizeof(double));
            if (!nf) return 0;
            col->f64 = nf;
        } else {
            int64_t *ni = realloc(col->i64, nc * sizeof(int64_t));
            if (!ni) return 0;
            col->i64 = ni;
        }
        col->cap = nc;
    }
    return 1;
}

static int ensure_blob(Col *col, int64_t extra) {
    if (col->blen + extra > col->bcap) {
        int64_t nc = col->bcap ? col->bcap * 2 : 4096;
        while (nc < col->blen + extra) nc *= 2;
        uint8_t *nb = realloc(col->blob, nc);
        if (!nb) return 0;
        col->blob = nb;
    }
    return 1;
}

static void push_i64(Col *col, int64_t v, int *err) {
    if (!ensure_cap(col, 1)) { *err = 1; return; }
    col->i64[col->len++] = v;
}

static void push_f64(Col *col, double v, int *err) {
    if (!ensure_cap(col, 1)) { *err = 1; return; }
    col->f64[col->len++] = v;
}

static void push_str(Col *col, const uint8_t *s, int64_t n, int *err) {
    if (!ensure_cap(col, 1) || !ensure_blob(col, n)) { *err = 1; return; }
    if (n) memcpy(col->blob + col->blen, s, n);
    col->blen += n;
    col->i64[col->len++] = col->blen; /* end offset */
}

static void skip_map(Cur *c) {
    while (!c->err) {
        int64_t n = read_varlong(c);
        if (n == 0) break;
        if (n < 0) { /* block byte size follows */
            if (n == INT64_MIN) { c->err = 1; return; } /* -n would be UB */
            read_varlong(c);
            n = -n;
        }
        for (int64_t i = 0; i < n && !c->err; i++) {
            for (int k = 0; k < 2 && !c->err; k++) { /* key + string value */
                int64_t len = read_varlong(c);
                /* compare against remaining bytes — `c->p + len` would be
                 * pointer-arithmetic overflow UB for adversarial lengths */
                if (len < 0 || len > (int64_t)(c->end - c->p)) { c->err = 1; return; }
                c->p += len;
            }
        }
    }
}

/* Execute a program segment.  null_mode: consume no input, append one
 * placeholder per leaf column (keeps columns row-aligned across optional
 * branches).  Arrays in null_mode record count 0 and emit no elements. */
static void exec_prog(Cur *c, const int32_t *prog, int64_t n, Col *cols,
                      int null_mode) {
    int64_t i = 0;
    while (i < n && !c->err) {
        int32_t op = prog[i++];
        switch (op) {
        case OP_LONG:
        case OP_ENUM: {
            Col *col = &cols[prog[i++]];
            push_i64(col, null_mode ? 0 : read_varlong(c), &c->err);
            break;
        }
        case OP_BOOL: {
            Col *col = &cols[prog[i++]];
            int64_t v = 0;
            if (!null_mode) {
                if (c->p >= c->end) { c->err = 1; break; }
                v = *c->p++;
            }
            push_i64(col, v, &c->err);
            break;
        }
        case OP_DOUBLE: {
            Col *col = &cols[prog[i++]];
            double v = 0.0 / 0.0; /* NaN placeholder */
            if (!null_mode) {
                if ((int64_t)(c->end - c->p) < 8) { c->err = 1; break; }
                memcpy(&v, c->p, 8);
                c->p += 8;
            }
            push_f64(col, v, &c->err);
            break;
        }
        case OP_FLOAT: {
            Col *col = &cols[prog[i++]];
            double v = 0.0 / 0.0;
            if (!null_mode) {
                float fv;
                if ((int64_t)(c->end - c->p) < 4) { c->err = 1; break; }
                memcpy(&fv, c->p, 4);
                c->p += 4;
                v = fv;
            }
            push_f64(col, v, &c->err);
            break;
        }
        case OP_STRING: {
            Col *col = &cols[prog[i++]];
            if (null_mode) {
                push_str(col, NULL, 0, &c->err);
            } else {
                int64_t len = read_varlong(c);
                if (len < 0 || len > (int64_t)(c->end - c->p)) { c->err = 1; break; }
                push_str(col, c->p, len, &c->err);
                c->p += len;
            }
            break;
        }
        case OP_OPT: {
            int32_t null_idx = prog[i++];
            int32_t present_col = prog[i++];
            int32_t body_len = prog[i++];
            int is_null = 1;
            if (!null_mode) {
                int64_t branch = read_varlong(c);
                if (branch != 0 && branch != 1) { c->err = 1; break; }
                is_null = (branch == null_idx);
            }
            if (present_col >= 0)
                push_i64(&cols[present_col], is_null ? 0 : 1, &c->err);
            exec_prog(c, prog + i, body_len, cols, is_null);
            i += body_len;
            break;
        }
        case OP_ARRAY: {
            int32_t count_col = prog[i++];
            int32_t body_len = prog[i++];
            int64_t total = 0;
            if (!null_mode) {
                while (!c->err) {
                    int64_t bn = read_varlong(c);
                    if (bn == 0) break;
                    if (bn < 0) {
                        if (bn == INT64_MIN) { c->err = 1; break; }
                        read_varlong(c);
                        bn = -bn;
                    }
                    for (int64_t j = 0; j < bn && !c->err; j++)
                        exec_prog(c, prog + i, body_len, cols, 0);
                    total += bn;
                }
            }
            if (count_col >= 0)
                push_i64(&cols[count_col], total, &c->err);
            i += body_len;
            break;
        }
        case OP_MAP_SKIP:
            if (!null_mode) skip_map(c);
            break;
        case OP_MAP: {
            int32_t count_col = prog[i++];
            int32_t key_col = prog[i++];
            int32_t val_col = prog[i++];
            int64_t total = 0;
            if (!null_mode) {
                while (!c->err) {
                    int64_t bn = read_varlong(c);
                    if (bn == 0) break;
                    if (bn < 0) {
                        if (bn == INT64_MIN) { c->err = 1; break; }
                        read_varlong(c);
                        bn = -bn;
                    }
                    for (int64_t j = 0; j < bn && !c->err; j++) {
                        int64_t len = read_varlong(c);
                        if (len < 0 || len > (int64_t)(c->end - c->p)) { c->err = 1; break; }
                        push_str(&cols[key_col], c->p, len, &c->err);
                        c->p += len;
                        len = read_varlong(c);
                        if (len < 0 || len > (int64_t)(c->end - c->p)) { c->err = 1; break; }
                        push_str(&cols[val_col], c->p, len, &c->err);
                        c->p += len;
                    }
                    total += bn;
                }
            }
            if (count_col >= 0)
                push_i64(&cols[count_col], total, &c->err);
            break;
        }
        default:
            c->err = 1;
        }
    }
}

/* Decode `nrecords` records from buf.  Returns bytes consumed, or -1. */
int64_t avrodec_decode_block(const uint8_t *buf, int64_t buflen,
                             int64_t nrecords, const int32_t *prog,
                             int64_t proglen, Col *cols, int32_t ncols) {
    (void)ncols;
    Cur c = {buf, buf + buflen, 0};
    for (int64_t r = 0; r < nrecords && !c.err; r++)
        exec_prog(&c, prog, proglen, cols, 0);
    if (c.err) return -1;
    return (int64_t)(c.p - buf);
}

Col *avrodec_alloc_cols(int32_t ncols, const int32_t *kinds) {
    Col *cols = calloc(ncols, sizeof(Col));
    if (!cols) return NULL;
    for (int32_t i = 0; i < ncols; i++) cols[i].kind = kinds[i];
    return cols;
}

void avrodec_free_cols(Col *cols, int32_t ncols) {
    if (!cols) return;
    for (int32_t i = 0; i < ncols; i++) {
        free(cols[i].i64);
        free(cols[i].f64);
        free(cols[i].blob);
    }
    free(cols);
}

/* Accessors (keep the struct layout private to C). */
int64_t avrodec_col_len(const Col *cols, int32_t i) { return cols[i].len; }
int64_t avrodec_col_blob_len(const Col *cols, int32_t i) { return cols[i].blen; }
const int64_t *avrodec_col_i64(const Col *cols, int32_t i) { return cols[i].i64; }
const double *avrodec_col_f64(const Col *cols, int32_t i) { return cols[i].f64; }
const uint8_t *avrodec_col_blob(const Col *cols, int32_t i) { return cols[i].blob; }
