"""FeedbackBuffer: bounded, deduplicating intake for labeled feedback.

The online tier's front door.  Observations arrive as request-shaped
batches (features per shard, raw ids per entity type, labels) and are
COALESCED PER ENTITY under each updatable coordinate: the updater drains
whole entities, so one entity with 40 pending rows costs one anchored
solve, not 40.

Discipline mirrors the serving micro-batcher's:

  * BOUNDED — `max_rows` pending lane-rows total; a batch that would
    overflow is rejected whole with `Overloaded` (the same backpressure
    exception the scoring path sheds with), never partially absorbed.
  * PER-ENTITY DEDUP WINDOW — each (coordinate, entity) keeps only the
    newest `entity_window` observations (older ones coalesce out: with a
    prior-anchored solve the newest rows carry the signal, and an
    unboundedly hot entity must not starve the buffer), and an optional
    per-observation `event_id` is checked against a sliding window of
    recently seen ids so client retries do not double-count feedback.
  * FIFO BY ENTITY — `drain` pops the entities whose oldest pending
    observation is oldest, so feedback-to-publish latency is fair under
    load.

Thread-safe; the buffer itself is scorer-agnostic (the updater resolves
ids -> table rows before offering).
"""
from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.serving.batcher import Overloaded
from photon_ml_tpu.utils import locktrace


@dataclasses.dataclass
class Observation:
    """One labeled row, shared by every coordinate lane it feeds (the
    feature dict carries ALL shards: the updater re-scores the row against
    the full model to build the residual offset)."""

    features: Dict[str, np.ndarray]     # shard -> [d_shard] row
    ids: Dict[str, object]              # re_type -> raw entity id
    label: float
    weight: float
    offset: float
    enqueued_at: float                  # monotonic clock at intake
    event_id: Optional[str] = None
    trace_id: Optional[str] = None      # propagated request id (X-Photon-
                                        # Trace): rides into the delta's
                                        # replication-record trace metadata
    enqueued_wall_s: float = 0.0        # wall clock at intake (fleet-
                                        # visible latency measures from it)


@dataclasses.dataclass
class EntityFeedback:
    """One drained entity: its pending observations, oldest first."""

    entity_id: object
    row: int                            # scorer table row (resolved at intake)
    observations: List[Observation]
    first_enqueued_at: float


class FeedbackBuffer:
    def __init__(self, max_rows: int = 8192, entity_window: int = 128,
                 dedup_window: int = 8192):
        if max_rows < 1 or entity_window < 1:
            raise ValueError("max_rows and entity_window must be >= 1")
        self.max_rows = int(max_rows)
        self.entity_window = int(entity_window)
        self.dedup_window = int(dedup_window)
        self._lock = locktrace.tracked(threading.Lock(),
                                       "FeedbackBuffer._lock")
        # lane -> OrderedDict[entity_id -> (row, deque[Observation])];
        # OrderedDict insertion order IS the FIFO drain order
        self._lanes: Dict[str, "OrderedDict[object, Tuple[int, deque]]"] = {}
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._pending = 0
        # intake accounting (the updater mirrors these into ServingMetrics)
        self.accepted = 0
        self.deduped = 0
        self.coalesced = 0
        self.shed = 0

    @property
    def pending_rows(self) -> int:
        with self._lock:
            return self._pending

    def pending_entities(self, lane: str) -> int:
        with self._lock:
            return len(self._lanes.get(lane, ()))

    def lanes(self) -> List[str]:
        with self._lock:
            return [lane for lane, ents in self._lanes.items() if ents]

    def _dedup(self, event_id: Optional[str]) -> bool:
        """True = drop (seen within the window).  Caller holds the lock."""
        if event_id is None:
            return False
        if event_id in self._seen:
            return True
        self._seen[event_id] = None
        while len(self._seen) > self.dedup_window:
            self._seen.popitem(last=False)
        return False

    def offer_batch(self, entries: List[Tuple[str, object, int, Observation]]
                    ) -> Dict[str, int]:
        """Enqueue (lane, entity_id, table_row, observation) entries as one
        atomic batch.  Duplicate event_ids are dropped first; if the
        remainder would push pending lane-rows past `max_rows`, the WHOLE
        batch is rejected with Overloaded (all-or-nothing, so a client
        retry after backoff re-offers a consistent batch)."""
        with self._lock:
            fresh = []
            deduped = 0
            # one event_id may legitimately fan out to several lanes
            # (userId AND itemId): dedup per EVENT, not per lane entry
            admitted_events: set = set()
            dropped_events: set = set()
            for lane, entity_id, row, obs in entries:
                eid = obs.event_id
                if eid is not None and eid in admitted_events:
                    fresh.append((lane, entity_id, row, obs))
                    continue
                if eid is not None and eid in dropped_events:
                    deduped += 1
                    continue
                if self._dedup(eid):
                    dropped_events.add(eid)
                    deduped += 1
                    continue
                if eid is not None:
                    admitted_events.add(eid)
                fresh.append((lane, entity_id, row, obs))
            # coalescing frees window overflow slots, so count the rows
            # that will actually remain pending
            if self._pending + len(fresh) > self.max_rows:
                overflow = sum(
                    1 for lane, entity_id, _row, _obs in fresh
                    if len(self._lanes.get(lane, {}).get(entity_id,
                                                         (0, ()))[1])
                    >= self.entity_window)
                if self._pending + len(fresh) - overflow > self.max_rows:
                    self.shed += 1
                    self.deduped += deduped
                    raise Overloaded(
                        f"feedback buffer full ({self._pending} pending "
                        f"rows, max {self.max_rows}); retry after the "
                        "updater drains")
            coalesced = 0
            for lane, entity_id, row, obs in fresh:
                ents = self._lanes.setdefault(lane, OrderedDict())
                slot = ents.get(entity_id)
                if slot is None:
                    slot = (row, deque(maxlen=self.entity_window))
                    ents[entity_id] = slot
                q = slot[1]
                if len(q) == self.entity_window:
                    coalesced += 1      # deque drops the oldest silently
                    self._pending -= 1
                q.append(obs)
                self._pending += 1
            self.accepted += len(fresh)
            self.deduped += deduped
            self.coalesced += coalesced
            return {"accepted": len(fresh), "deduped": deduped,
                    "coalesced": coalesced, "pending_rows": self._pending}

    def drain(self, lane: str, max_entities: int) -> List[EntityFeedback]:
        """Pop up to `max_entities` whole entities from a lane (FIFO by
        first-pending time)."""
        out: List[EntityFeedback] = []
        with self._lock:
            ents = self._lanes.get(lane)
            if not ents:
                return out
            while ents and len(out) < max_entities:
                entity_id, (row, q) = ents.popitem(last=False)
                obs = list(q)
                self._pending -= len(obs)
                out.append(EntityFeedback(
                    entity_id=entity_id, row=row, observations=obs,
                    first_enqueued_at=min(o.enqueued_at for o in obs)))
        return out

    def requeue(self, lane: str, drained: List[EntityFeedback]) -> None:
        """Put drained entities back (stale delta / transient publish
        failure): their observations keep the original enqueue times, so
        feedback-to-publish latency stays honest.  Bypasses the max_rows
        bound — these rows were already admitted once."""
        with self._lock:
            ents = self._lanes.setdefault(lane, OrderedDict())
            for ef in drained:
                slot = ents.get(ef.entity_id)
                if slot is None:
                    slot = (ef.row, deque(maxlen=self.entity_window))
                    ents[ef.entity_id] = slot
                    ents.move_to_end(ef.entity_id, last=False)
                q = slot[1]
                for obs in reversed(ef.observations):
                    if len(q) == self.entity_window:
                        break  # window full: newest survive
                    q.appendleft(obs)
                    self._pending += 1

    def drop_entity(self, lane: str, entity_id) -> int:
        """Discard an entity's pending rows (it was frozen)."""
        with self._lock:
            ents = self._lanes.get(lane)
            if not ents or entity_id not in ents:
                return 0
            _row, q = ents.pop(entity_id)
            self._pending -= len(q)
            return len(q)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pending_rows": self._pending,
                    "accepted": self.accepted, "deduped": self.deduped,
                    "coalesced": self.coalesced, "shed": self.shed}
