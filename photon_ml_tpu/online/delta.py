"""ModelDelta: row-level model updates for the live scorer.

A delta is the ONLINE counterpart of a full-model hot swap: instead of
building + warming a whole new CompiledScorer, it carries only the CHANGED
rows of the stacked random-effect tables (per coordinate: row indices, new
row values, and the pre-delta row values for exact rollback) plus a version
vector `(base_version, seq)` that pins which full-model version the rows
were solved against — the registry refuses to scatter a delta onto any
other version (StaleDeltaError), because rows solved against stale
residual margins would silently corrupt the live table.

This module is deliberately dependency-light (numpy only): deltas cross
process boundaries (models/io.py serializes them durably) and must stay
importable without pulling the serving or JAX stack.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np


@dataclasses.dataclass
class CoordinateDelta:
    """Changed rows of ONE coordinate's stacked [E, d] table."""

    rows: np.ndarray        # [k] int table-row indices (unique)
    values: np.ndarray      # [k, d] new row values
    prior: np.ndarray       # [k, d] pre-delta row values (rollback source)

    def __post_init__(self):
        self.rows = np.asarray(self.rows, dtype=np.int64)
        self.values = np.asarray(self.values)
        self.prior = np.asarray(self.prior)
        if self.rows.ndim != 1:
            raise ValueError(f"rows must be [k], got shape {self.rows.shape}")
        k = len(self.rows)
        for name, a in (("values", self.values), ("prior", self.prior)):
            if a.ndim != 2 or a.shape[0] != k:
                raise ValueError(
                    f"{name} must be [{k}, d], got shape {a.shape}")
        if self.values.shape != self.prior.shape:
            raise ValueError(
                f"values {self.values.shape} and prior {self.prior.shape} "
                "must agree")
        if len(np.unique(self.rows)) != k:
            raise ValueError("delta rows must be unique (duplicate row "
                             "updates within one delta are ambiguous)")
        if (self.rows < 0).any():
            raise ValueError("delta rows must be non-negative table indices")

    @property
    def num_rows(self) -> int:
        return len(self.rows)


@dataclasses.dataclass
class ModelDelta:
    """Row updates for one or more coordinates + the version vector.

    `base_version` is the full-model version the rows were solved against
    (and the only version they may be applied to); `seq` is the publisher's
    monotonically increasing delta sequence number within that version —
    together they form the version vector surfaced on /healthz and in
    ServingMetrics."""

    base_version: str
    seq: int
    coordinates: Dict[str, CoordinateDelta]
    created_at: float = 0.0          # wall-clock time.time() at build
    #: cross-process trace metadata (telemetry.distributed): the sampled
    #: propagated request ids this delta aggregates, the publisher's
    #: update-cycle span ref, and the oldest intake wall time — rides the
    #: replication record so replica applies join the same trace tree.
    #: Optional and JSON-plain; bit-identity of the model state never
    #: depends on it.
    trace: Dict[str, object] = None

    def __post_init__(self):
        if not self.coordinates:
            raise ValueError("a ModelDelta must update at least one "
                             "coordinate")

    @property
    def num_rows(self) -> int:
        return sum(cd.num_rows for cd in self.coordinates.values())

    def version_vector(self) -> Dict[str, object]:
        return {"base_version": self.base_version, "delta_seq": self.seq}

    def summary(self) -> str:
        per = ", ".join(f"{name}:{cd.num_rows}"
                        for name, cd in sorted(self.coordinates.items()))
        return (f"ModelDelta(base={self.base_version}, seq={self.seq}, "
                f"rows=[{per}])")

    # -- flat array form (what models/io.py persists) ----------------------

    def to_arrays(self) -> Dict[str, np.ndarray]:
        """Flatten to named numpy arrays (npz-ready); metadata rides
        separately (models/io.save_model_delta)."""
        out: Dict[str, np.ndarray] = {}
        for name, cd in self.coordinates.items():
            if "::" in name:
                raise ValueError(f"coordinate name {name!r} may not contain "
                                 "'::' (the array-key delimiter)")
            out[f"delta::{name}::rows"] = cd.rows
            out[f"delta::{name}::values"] = cd.values
            out[f"delta::{name}::prior"] = cd.prior
        return out

    @staticmethod
    def from_arrays(arrays: Dict[str, np.ndarray], base_version: str,
                    seq: int, created_at: float = 0.0) -> "ModelDelta":
        names = {k.split("::")[1] for k in arrays if k.startswith("delta::")}
        coords = {
            name: CoordinateDelta(rows=arrays[f"delta::{name}::rows"],
                                  values=arrays[f"delta::{name}::values"],
                                  prior=arrays[f"delta::{name}::prior"])
            for name in sorted(names)}
        return ModelDelta(base_version=base_version, seq=seq,
                          coordinates=coords, created_at=created_at)
