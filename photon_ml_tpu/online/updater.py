"""OnlineUpdater: re-solve touched entities, publish row-level deltas.

The background loop of the online tier.  Each cycle it drains pending
entities from the FeedbackBuffer (per updatable coordinate), groups them
into the batched random-effect solver's padded layout — entity lanes fixed
at `micro_batch`, samples padded to a power-of-two S-bucket, exactly the
shape discipline training's RandomEffectDataset uses — and runs ONE
anchored batched solve (game/anchored.py) warm-started at the current
coefficients.  The changed rows then scatter into the live scorer as a
ModelDelta under the registry lock: no full-model cutover, no fresh XLA
traces (solver, fold, gather and scatter programs are all keyed on the
bounded (micro_batch, S-bucket, d) shape set).

Residual algebra: the anchored delta-space subproblem needs each row's
offset to be `base_offset + margin of every OTHER coordinate + x . c0`,
and since the full model margin already contains `x . c0`, that is simply
`base_offset + full-model margin` — one scorer.score() call per
micro-batch, no per-coordinate margin decomposition (see
game/anchored.py).

Containment mirrors chunk staging's discipline (utils/faults.py sites
`online.solve` / `online.publish`): transient failures retry with jittered
exponential backoff; a non-finite solved row FREEZES that entity (its
row never reaches the live table, later feedback for it is dropped and
counted) — quarantine, not poison.  A full-model swap racing a publish
surfaces as StaleDeltaError: the feedback re-enqueues and re-solves
against the new version next cycle.
"""
# photonlint: flush-point markers below: the updater thread's readbacks
# (solved rows, finite flags, margins) ARE its flush boundary — each cycle
# does one batched device round-trip per coordinate.
from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import distributed
from photon_ml_tpu.telemetry.timings import clock

from photon_ml_tpu.game.anchored import lane_all_finite, solve_anchored
from photon_ml_tpu.online.delta import CoordinateDelta, ModelDelta
from photon_ml_tpu.online.feedback import (EntityFeedback, FeedbackBuffer,
                                           Observation)
from photon_ml_tpu.ops import losses as L
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.parallel.random_effect import EntityBlocks
from photon_ml_tpu.serving.registry import StaleDeltaError
from photon_ml_tpu.utils import faults, locktrace
from photon_ml_tpu.utils.math import ceil_pow2

logger = logging.getLogger("photon_ml_tpu")

#: padding label value valid for every loss family (mask zeroes the cell)
_SAFE_LABEL = 0.5


@dataclasses.dataclass(frozen=True)
class OnlineUpdateConfig:
    """Knobs of the online tier (cli.serve --enable-updates maps 1:1)."""

    micro_batch: int = 16           # entity lanes per anchored solve (pow-2)
    max_rows_per_entity: int = 64   # S ceiling (pow-2); newest rows win
    min_rows_bucket: int = 4        # smallest padded S-bucket
    anchor_weight: float = 1.0      # lambda of the ||c - c0||^2 prior pull
    max_iterations: int = 100       # per-entity LBFGS cap
    tolerance: float = 1e-9
    interval_s: float = 0.02        # idle poll period of the update loop
    max_pending_rows: int = 8192    # buffer bound -> Overloaded
    entity_window: int = 128        # per-entity coalescing window
    dedup_window: int = 8192        # event-id dedup window
    max_attempts: int = 3           # transient solve/publish retries
    backoff_s: float = 0.02         # base of the jittered exp backoff

    def __post_init__(self):
        if self.micro_batch < 1 or self.max_rows_per_entity < 1:
            raise ValueError("micro_batch and max_rows_per_entity must be "
                             ">= 1")
        if self.entity_window > self.max_rows_per_entity:
            # more window than solve capacity would silently discard the
            # overflow at solve time; clamp loudly instead
            object.__setattr__(self, "entity_window",
                               self.max_rows_per_entity)

    @property
    def lanes_pow2(self) -> int:
        return int(ceil_pow2(self.micro_batch))


class OnlineUpdater:
    """Accepts labeled feedback, re-solves ONLY the touched entities'
    anchored subproblems, and publishes delta swaps into the live scorer.

    `submit()` is the intake (thread-safe, called from request threads);
    `run_once()` is one drain-solve-publish cycle (the background loop
    calls it; tests and the bench call it directly for determinism)."""

    def __init__(self, registry, metrics=None,
                 config: OnlineUpdateConfig = OnlineUpdateConfig(),
                 emitter=None, health=None, feedback_log=None):
        """`health` (a health.HealthMonitor) receives per-delta magnitude
        and freeze vitals, and is what `pause()`/`resume()` exist for:
        the monitor's gates stop the update loop while the model is
        degrading and restart it on recovery.  `feedback_log` (a
        fleet.FeedbackLog) makes every admitted batch durable before
        intake returns — the refit compactor's complete replay source."""
        self.registry = registry
        self.metrics = metrics
        self.config = config
        self.emitter = emitter
        self.health = health
        self.feedback_log = feedback_log
        self.buffer = FeedbackBuffer(max_rows=config.max_pending_rows,
                                     entity_window=config.entity_window,
                                     dedup_window=config.dedup_window)
        self._solver = OptimizerConfig(max_iterations=config.max_iterations,
                                       tolerance=config.tolerance)
        # mutable updater state crosses three threads (request intake, the
        # background loop, operator introspection): everything below is
        # guarded by _state_lock — photonlint PH010/PH013 enforce it, and
        # the armed locktrace tracker observes it in the stress test
        self._state_lock = locktrace.tracked(threading.Lock(),
                                             "OnlineUpdater._state_lock")
        self._frozen: set = set()    # (lane, entity_id)  # photonlint: guarded-by=_state_lock
        self._thread: Optional[threading.Thread] = None   # photonlint: guarded-by=_state_lock
        self.cycles = 0                                   # photonlint: guarded-by=_state_lock
        self.deltas_published = 0                         # photonlint: guarded-by=_state_lock
        self.last_error: Optional[str] = None             # photonlint: guarded-by=_state_lock
        self._paused = False                              # photonlint: guarded-by=_state_lock
        self.pause_reason: Optional[str] = None           # photonlint: guarded-by=_state_lock
        self._last_cycle_at: Optional[float] = None       # photonlint: guarded-by=_state_lock
        self._drain_rate: float = 0.0                     # photonlint: guarded-by=_state_lock
        self._wake = threading.Event()
        self._closed = threading.Event()
        self._jitter = random.Random(0xC0FFEE)
        self.warmed = False
        self.warmup_s = 0.0

    # -- intake -------------------------------------------------------------

    def submit(self, features: Dict[str, np.ndarray],
               ids: Dict[str, np.ndarray], labels: np.ndarray,
               weights: Optional[np.ndarray] = None,
               offsets: Optional[np.ndarray] = None,
               event_ids: Optional[List[str]] = None) -> Dict[str, int]:
        """Enqueue a labeled feedback batch (request-shaped: features per
        shard, raw ids per entity type, labels per row).  Returns intake
        accounting; raises Overloaded when the buffer is full.  Rows whose
        entity is unseen by a coordinate (no table row to anchor at) or
        frozen (quarantined by a non-finite solve) are dropped for that
        coordinate and counted."""
        scorer = self.registry.scorer
        n = scorer.validate_request(features, ids)
        labels = np.asarray(labels, np.float64)
        if labels.shape != (n,):
            raise ValueError(f"labels must be [{n}], got {labels.shape}")
        weights_a = (np.ones(n) if weights is None
                     else np.asarray(weights, np.float64))
        offsets_a = (np.zeros(n) if offsets is None
                     else np.asarray(offsets, np.float64))
        for name, a in (("weights", weights_a), ("offsets", offsets_a)):
            if a.shape != (n,):
                raise ValueError(f"{name} must be [{n}], got {a.shape}")
        if event_ids is not None and len(event_ids) != n:
            raise ValueError(f"event_ids must have {n} entries, got "
                             f"{len(event_ids)}")
        feats = {s: np.asarray(x) for s, x in features.items()}
        now = clock()
        wall_now = time.time()
        trace_id = distributed.current_request_id()
        entries: List[Tuple[str, object, int, Observation]] = []
        unseen = frozen = 0
        lane_meta = scorer.updatable_coordinates()
        # one coherent snapshot of the quarantine set for the whole batch
        # (the updater thread freezes entities concurrently) [PH010]
        with self._state_lock:
            frozen_now = set(self._frozen)
        for i in range(n):
            obs = Observation(
                features={s: feats[s][i] for s in feats},
                ids={t: np.asarray(ids[t])[i] for t in ids},
                label=float(labels[i]), weight=float(weights_a[i]),
                offset=float(offsets_a[i]), enqueued_at=now,
                event_id=None if event_ids is None else event_ids[i],
                trace_id=trace_id, enqueued_wall_s=wall_now)
            for lane, _shard, re_type in lane_meta:
                entity_id = obs.ids.get(re_type)
                row = scorer.entity_row(lane, entity_id)
                if row < 0:
                    unseen += 1
                    continue
                if (lane, entity_id) in frozen_now:
                    frozen += 1
                    continue
                entries.append((lane, entity_id, row, obs))
        try:
            out = self.buffer.offer_batch(entries)
        except Exception:
            if self.metrics is not None:
                self.metrics.observe_feedback_shed()
            raise
        if self.feedback_log is not None:
            # durable BEFORE intake returns: an admitted batch the refit
            # compactor can never replay is an admitted batch lost to the
            # next full refit
            self._persist_feedback_with_retry(
                feats, ids, labels, weights_a, offsets_a,
                event_ids=event_ids, trace_id=trace_id, wall_s=wall_now)
        out.update({"rows": n, "dropped_unseen": unseen,
                    "dropped_frozen": frozen})
        if self.metrics is not None:
            self.metrics.observe_feedback(
                rows=n, lane_rows=out["accepted"], unseen=unseen,
                frozen=frozen, deduped=out["deduped"],
                coalesced=out["coalesced"])
        self._wake.set()
        return out

    # -- warmup -------------------------------------------------------------

    def warmup(self) -> float:
        """Pre-compile every program an update cycle can need — the
        anchored batched solver at each pow-2 S-bucket, the prior
        gather/mask chain, and the delta scatter at each pow-2 row count —
        so no feedback stream ever traces (the online twin of
        CompiledScorer.warmup; the background loop runs this before its
        first drain)."""
        from photon_ml_tpu.serving.scorer import _pad_pow2_rows, _scatter_rows
        cfg = self.config
        scorer = self.registry.scorer
        t0 = clock()
        E = cfg.lanes_pow2
        bt = jnp.dtype(jax.dtypes.canonicalize_dtype(np.float64))
        with telemetry.span("online_warmup"):
            for lane, shard, _re_type in scorer.updatable_coordinates():
                d = scorer.feature_shards[shard]
                table = scorer.re_table(lane)
                # the prior prep chain (gather on table dtype -> mask ->
                # cast to the block dtype), exactly as a cycle runs it
                rows0 = np.zeros(E, np.int64)
                prior_t = scorer.gather_rows(lane, rows0)
                prior = jnp.where(jnp.asarray(rows0 >= 0)[:, None],
                                  prior_t, 0.0).astype(bt)
                S = int(ceil_pow2(cfg.min_rows_bucket))
                s_max = int(ceil_pow2(cfg.max_rows_per_entity))
                while True:
                    blocks = EntityBlocks(
                        x=jnp.zeros((E, S, d), bt),
                        labels=jnp.full((E, S), _SAFE_LABEL, bt),
                        mask=jnp.zeros((E, S), bt),
                        weights=jnp.zeros((E, S), bt),
                        offsets=jnp.zeros((E, S), bt))
                    new_rows, _res = solve_anchored(
                        blocks, prior, self._loss(), self._solver,
                        cfg.anchor_weight)
                    jax.block_until_ready(lane_all_finite(new_rows))
                    if S >= s_max:
                        break
                    S <<= 1
                # scatter programs: one per pow-2 delta row count (results
                # discarded — the live table is never touched)
                k = 1
                while k <= E:
                    rows = np.arange(min(k, table.shape[0]), dtype=np.int64)
                    vals = np.zeros((len(rows), table.shape[1]))
                    rows_p, vals_p = _pad_pow2_rows(rows, vals,
                                                    table.shape[0])
                    jax.block_until_ready(_scatter_rows(
                        table, jnp.asarray(rows_p),
                        jnp.asarray(vals_p, table.dtype)))
                    k <<= 1
        self.warmup_s = clock() - t0
        self.warmed = True
        return self.warmup_s

    # -- the update cycle ---------------------------------------------------

    def run_once(self) -> Dict[str, int]:
        """One drain-solve-publish cycle over every coordinate with
        pending feedback.  Returns {"entities": ..., "rows": ...,
        "deltas": ...} for what was published.  A no-op while paused
        (health gate / operator): pending feedback stays buffered."""
        totals = {"entities": 0, "rows": 0, "deltas": 0}
        if self.paused:
            return totals
        t0 = clock()
        scorer = self.registry.scorer  # ONE version for the whole cycle
        for lane, shard, re_type in scorer.updatable_coordinates():
            if self.buffer.pending_entities(lane) == 0:
                continue
            drained = self.buffer.drain(lane, self.config.micro_batch)
            if not drained:
                continue
            # the propagated request ids this cycle aggregates: the span
            # attr (and the delta's replication-trace metadata) is what
            # lets `cli.trace merge` stitch a /feedback request through
            # the asynchronous cycle into one tree
            trace_ids, oldest_wall = self._trace_meta(drained)
            with telemetry.span("online_update", coordinate=lane,
                                entities=len(drained),
                                request_ids=",".join(trace_ids)):
                published = self._solve_and_publish(
                    scorer, lane, shard, drained,
                    trace_ids=trace_ids, oldest_wall=oldest_wall)
            if published:
                totals["entities"] += published["entities"]
                totals["rows"] += published["rows"]
                totals["deltas"] += 1
        cycle_s = clock() - t0
        with self._state_lock:
            self._last_cycle_at = clock()
            if totals["rows"] and cycle_s > 0:
                # EMA of lane-rows drained per second: what the 429
                # Retry-After derivation divides the backlog by
                rate = totals["rows"] / cycle_s
                self._drain_rate = (rate if self._drain_rate == 0.0 else
                                    0.7 * self._drain_rate + 0.3 * rate)
        return totals

    def flush(self, max_cycles: int = 1000) -> Dict[str, int]:
        """Drain the buffer to empty (tests / bench determinism)."""
        totals = {"entities": 0, "rows": 0, "deltas": 0}
        for _ in range(max_cycles):
            if not self.buffer.lanes() or self.paused:
                break
            out = self.run_once()
            for k in totals:
                totals[k] += out[k]
            if out["deltas"] == 0 and out["entities"] == 0:
                break  # nothing publishable remains (all frozen/stale)
        return totals

    # -- health gating --------------------------------------------------------

    def pause(self, reason: Optional[str] = None) -> None:
        """Stop publishing updates (the loop idles; `submit` keeps
        buffering so recovery detection still sees labels).  Idempotent."""
        with self._state_lock:
            if self._paused:
                return
            self._paused = True
            self.pause_reason = reason
        telemetry.event("online_updates_paused", reason=str(reason))
        logger.warning("online updates PAUSED (%s)", reason)

    def resume(self) -> None:
        """Resume publishing; buffered feedback drains on the next cycle."""
        with self._state_lock:
            if not self._paused:
                return
            self._paused = False
            self.pause_reason = None
        telemetry.event("online_updates_resumed")
        logger.info("online updates resumed")
        self._wake.set()

    @property
    def paused(self) -> bool:
        with self._state_lock:
            return self._paused

    def retry_after_s(self) -> float:
        """How long a 429'd feedback client should wait before retrying,
        derived from the updater's observed drain rate: the pending
        backlog divided by the EMA of lane-rows drained per second
        (clamped to [interval_s, 30]).  Before the first drain there is
        no rate yet — the poll interval is the honest floor."""
        pending = self.buffer.pending_rows
        with self._state_lock:
            rate = self._drain_rate
        if rate <= 0.0:
            return max(self.config.interval_s, 0.05)
        return float(min(max(pending / rate, self.config.interval_s, 0.05),
                         30.0))

    def last_cycle_age_s(self) -> Optional[float]:
        """Seconds since the last completed update cycle (None before
        the first)."""
        with self._state_lock:
            last = self._last_cycle_at
        return None if last is None else clock() - last

    def alive(self) -> bool:
        """Is the background loop thread running?  (False under manual
        `run_once()` driving — tests/bench — and after close().)"""
        with self._state_lock:
            thread = self._thread
        return thread is not None and thread.is_alive()

    def probe(self) -> Dict[str, object]:
        """Live vitals for the metric surfaces and /healthz (refreshed at
        render by ServingMetrics._refresh_online_gauges)."""
        with self._state_lock:
            frozen = len(self._frozen)
            paused = self._paused
            reason = self.pause_reason
            last = self._last_cycle_at
            thread = self._thread
        return {"frozen": frozen, "paused": paused, "pause_reason": reason,
                "alive": thread is not None and thread.is_alive(),
                "last_cycle_age_s": (None if last is None
                                     else clock() - last)}

    #: distinct request ids carried per update cycle / delta record (the
    #: trace metadata is a sample, not an unbounded join table)
    MAX_TRACE_IDS = 16

    @classmethod
    def _trace_meta(cls, drained: List[EntityFeedback]):
        """-> (distinct propagated request ids, oldest intake wall time)
        across the drained entities' observations."""
        ids: List[str] = []
        seen = set()
        oldest = None
        for ef in drained:
            for obs in ef.observations:
                w = obs.enqueued_wall_s
                if w and (oldest is None or w < oldest):
                    oldest = w
                t = obs.trace_id
                if t and t not in seen and len(ids) < cls.MAX_TRACE_IDS:
                    seen.add(t)
                    ids.append(t)
        return ids, oldest

    def _blocks_for(self, scorer, shard: str,
                    drained: List[EntityFeedback]):
        """Drained entities -> the batched solver's padded layout:
        [micro_batch lanes, pow-2 S, d] blocks + the flat request that
        prices every real row's full-model margin."""
        cfg = self.config
        E = cfg.lanes_pow2
        d = scorer.feature_shards[shard]
        s_real = max(len(ef.observations) for ef in drained)
        S = int(min(max(int(ceil_pow2(s_real)), cfg.min_rows_bucket),
                    int(ceil_pow2(cfg.max_rows_per_entity))))
        x = np.zeros((E, S, d))
        labels = np.full((E, S), _SAFE_LABEL)
        mask = np.zeros((E, S))
        weights = np.zeros((E, S))
        offsets = np.zeros((E, S))
        flat_feats = {s: [] for s in scorer.feature_shards}
        flat_ids = {t: [] for t in scorer.entity_types}
        cells: List[Tuple[int, int]] = []
        for e, ef in enumerate(drained):
            obs_list = ef.observations[-cfg.max_rows_per_entity:]
            for s, obs in enumerate(obs_list):
                x[e, s] = obs.features[shard]
                labels[e, s] = obs.label
                mask[e, s] = 1.0
                weights[e, s] = obs.weight
                offsets[e, s] = obs.offset
                for sh in flat_feats:
                    flat_feats[sh].append(obs.features[sh])
                for t in flat_ids:
                    flat_ids[t].append(obs.ids[t])
                cells.append((e, s))
        feats = {s: np.stack(v) for s, v in flat_feats.items()}
        ids = {t: np.asarray(v, dtype=object) for t, v in flat_ids.items()}
        # full-model margins against THIS scorer version: own-coordinate
        # contribution included, which is exactly the delta-space fold
        margins = scorer.score(feats, ids).scores
        for (e, s), m in zip(cells, margins):
            offsets[e, s] += m
        rows = np.full(E, -1, np.int64)
        rows[:len(drained)] = [ef.row for ef in drained]
        blocks = EntityBlocks(
            x=jnp.asarray(x), labels=jnp.asarray(labels),
            mask=jnp.asarray(mask), weights=jnp.asarray(weights),
            offsets=jnp.asarray(offsets))
        return blocks, rows, len(cells)

    def _persist_feedback_with_retry(self, feats, ids, labels, weights,
                                     offsets, *, event_ids, trace_id,
                                     wall_s) -> int:
        """Append one admitted batch to the durable feedback lane under
        the standard transient retry/backoff discipline (the lane's
        `replog.append` fault site fires with kind="feedback"), then
        refresh the fleet.log_records/log_bytes gauges."""
        from photon_ml_tpu.fleet.replog import record_for_feedback
        cfg = self.config
        rec = record_for_feedback(feats, ids, labels, weights, offsets,
                                  event_ids=event_ids, trace_id=trace_id,
                                  wall_s=wall_s)
        attempt = 0
        while True:
            attempt += 1
            try:
                seq = self.feedback_log.append(rec)
                break
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                if not faults.is_transient(e) or attempt >= cfg.max_attempts:
                    raise
                telemetry.event("online_feedback_log_retry",
                                attempt=attempt,
                                error=f"{type(e).__name__}: {e}")
                time.sleep(cfg.backoff_s * (2 ** (attempt - 1))
                           * (1.0 + 0.25 * self._jitter.random()))
        if self.metrics is not None:
            self.metrics.observe_feedback_log(
                records=self.feedback_log.live_records(),
                bytes=self.feedback_log.live_bytes())
        return seq

    def _solve_with_retry(self, lane: str, blocks, prior):
        """The anchored solve under the staging retry discipline:
        transient failures back off and retry; `poison` corrupts the
        solved rows so the freeze path is exercised end to end."""
        cfg = self.config
        attempt = 0
        while True:
            attempt += 1
            try:
                action = faults.fire("online.solve", coordinate=lane)
                new_rows, res = solve_anchored(
                    blocks, prior, self._loss(), self._solver,
                    cfg.anchor_weight)
                if action == "poison":
                    new_rows = new_rows * jnp.nan
                finite = np.asarray(  # photonlint: disable=PH001 -- the cycle's one batched readback: solved rows + finite flags
                    lane_all_finite(new_rows))
                return np.asarray(new_rows), finite, res
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                if not faults.is_transient(e) or attempt >= cfg.max_attempts:
                    raise
                if self.metrics is not None:
                    self.metrics.observe_solve_retry()
                telemetry.event("online_solve_retry", coordinate=lane,
                                attempt=attempt,
                                error=f"{type(e).__name__}: {e}")
                time.sleep(cfg.backoff_s * (2 ** (attempt - 1))
                           * (1.0 + 0.25 * self._jitter.random()))

    def _note_error(self, exc: BaseException) -> str:
        msg = f"{type(exc).__name__}: {exc}"
        with self._state_lock:
            self.last_error = msg
        return msg

    def _loss(self):
        task = self.registry.scorer.model.task_type
        loss = L.TASK_LOSSES.get(task)
        if loss is None:
            raise ValueError(f"task {task!r} has no pointwise loss to "
                             "refit against")
        return loss

    def _solve_and_publish(self, scorer, lane: str, shard: str,
                           drained: List[EntityFeedback],
                           trace_ids: Optional[List[str]] = None,
                           oldest_wall: Optional[float] = None
                           ) -> Optional[Dict[str, int]]:
        cfg = self.config
        t0 = clock()
        blocks, rows, num_rows = self._blocks_for(scorer, shard, drained)
        prior = scorer.gather_rows(lane, np.maximum(rows, 0))
        prior = jnp.where(jnp.asarray(rows >= 0)[:, None], prior,
                          0.0).astype(blocks.x.dtype)
        try:
            new_rows, finite, _res = self._solve_with_retry(lane, blocks,
                                                            prior)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            # a fatal solve failure drops the micro-batch: re-enqueueing
            # would retry a deterministic failure forever
            msg = self._note_error(e)
            if self.metrics is not None:
                self.metrics.observe_solve_failure()
            telemetry.event("online_solve_failed", coordinate=lane,
                            error=msg)
            logger.warning("online solve failed for %r: %s", lane, msg)
            return None
        if self.metrics is not None:
            self.metrics.observe_update_cycle(entities=len(drained),
                                              rows=num_rows)
        keep_rows, keep_values, keep_prior, latencies = [], [], [], []
        now = clock()
        prior_np = np.asarray(prior)  # photonlint: disable=PH001 -- delta prior rows leave the device exactly once per cycle
        for e, ef in enumerate(drained):
            if not finite[e]:
                # quarantine: the non-finite row NEVER reaches the live
                # table; the entity freezes until an operator full-refit
                with self._state_lock:
                    self._frozen.add((lane, ef.entity_id))
                self.buffer.drop_entity(lane, ef.entity_id)
                if self.metrics is not None:
                    self.metrics.observe_frozen_entity()
                if self.health is not None:
                    self.health.observe_freeze(lane)
                telemetry.event("online_quarantine", coordinate=lane,
                                entity=str(ef.entity_id))
                logger.warning("online solve for %r entity %r produced "
                               "non-finite coefficients: entity FROZEN "
                               "(live table untouched)", lane, ef.entity_id)
                continue
            keep_rows.append(ef.row)
            keep_values.append(new_rows[e])
            keep_prior.append(prior_np[e])
            latencies.append(now - ef.first_enqueued_at)
        if not keep_rows:
            return None
        if self.paused:
            # a health gate paused us MID-CYCLE (and may be rolling the
            # pending deltas back): rows solved against the pre-pause
            # state must not land after the rollback — requeue them and
            # let the post-recovery cycle re-solve against whatever
            # model is live then
            self.buffer.requeue(lane, drained)
            telemetry.event("online_publish_skipped_paused",
                            coordinate=lane)
            return None
        delta = ModelDelta(
            base_version=scorer.version, seq=self.registry.next_delta_seq(),
            coordinates={lane: CoordinateDelta(
                rows=np.asarray(keep_rows, np.int64),
                values=np.stack(keep_values),
                prior=np.stack(keep_prior))},
            created_at=time.time(),
            trace={"request_ids": list(trace_ids or ()),
                   "parent": distributed.span_ref(
                       telemetry.current_span_id()),
                   "enqueued_wall_s": oldest_wall})
        try:
            self._publish_with_retry(lane, delta, t0)
        except StaleDeltaError:
            # a full swap landed between solve and publish: the rows were
            # solved against a superseded model — re-enqueue and re-solve
            # against the new version next cycle
            if self.metrics is not None:
                self.metrics.observe_stale_delta()
            telemetry.event("online_stale_delta", coordinate=lane,
                            base_version=str(delta.base_version))
            self.buffer.requeue(lane, drained)
            self._wake.set()
            return None
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            msg = self._note_error(e)
            if self.metrics is not None:
                self.metrics.observe_solve_failure()
            telemetry.event("online_publish_failed", coordinate=lane,
                            error=msg)
            logger.warning("online publish failed for %r: %s (feedback "
                           "re-enqueued)", lane, msg)
            self.buffer.requeue(lane, drained)
            return None
        if self.metrics is not None:
            for lat in latencies:
                self.metrics.observe_feedback_to_publish(lat)
        if self.health is not None:
            # delta-magnitude vitals: L2 of each published row's move away
            # from its prior (the health monitor gates on the window max)
            self.health.observe_published(
                lane, np.linalg.norm(
                    np.stack(keep_values) - np.stack(keep_prior), axis=1))
        with self._state_lock:
            self.deltas_published += 1
        return {"entities": len(keep_rows), "rows": num_rows}

    def _publish_with_retry(self, lane: str, delta: ModelDelta,
                            t0: float) -> None:
        cfg = self.config
        attempt = 0
        while True:
            attempt += 1
            try:
                self.registry.apply_delta(delta, publish_s=clock() - t0)
                return
            except (KeyboardInterrupt, SystemExit, StaleDeltaError):
                raise
            except BaseException as e:
                if not faults.is_transient(e) or attempt >= cfg.max_attempts:
                    raise
                if self.metrics is not None:
                    self.metrics.observe_publish_retry()
                telemetry.event("online_publish_retry", coordinate=lane,
                                attempt=attempt,
                                error=f"{type(e).__name__}: {e}")
                time.sleep(cfg.backoff_s * (2 ** (attempt - 1))
                           * (1.0 + 0.25 * self._jitter.random()))

    # -- introspection ------------------------------------------------------

    def frozen_entities(self) -> List[Tuple[str, object]]:
        with self._state_lock:
            return sorted(self._frozen, key=str)

    def stats(self) -> Dict[str, object]:
        buffer_stats = self.buffer.stats()   # buffer takes its own lock
        with self._state_lock:
            return {"cycles": self.cycles,
                    "deltas_published": self.deltas_published,
                    "frozen": len(self._frozen),
                    "paused": self._paused,
                    "pause_reason": self.pause_reason,
                    "buffer": buffer_stats,
                    "last_error": self.last_error}

    # -- background loop ----------------------------------------------------

    def start(self) -> None:
        # test and spawn under the lock: two racing start() calls must
        # not each launch a loop thread [PH013 check-then-act]
        with self._state_lock:
            if self._thread is not None:
                return
            self._closed.clear()
            thread = threading.Thread(target=self._loop, daemon=True,
                                      name="photon-online-updater")
            self._thread = thread
        thread.start()

    def _loop(self) -> None:
        try:
            if not self.warmed:
                self.warmup()
        except Exception as e:  # a failed warmup must not kill the loop
            logger.exception("online updater warmup failed: %s",
                             self._note_error(e))
        while not self._closed.is_set():
            self._wake.wait(timeout=self.config.interval_s)
            self._wake.clear()
            if self._closed.is_set():
                break
            try:
                while self.buffer.lanes() and not self._closed.is_set():
                    with self._state_lock:
                        self.cycles += 1
                    out = self.run_once()
                    if out["deltas"] == 0 and out["entities"] == 0:
                        break  # nothing publishable; wait for fresh rows
            except Exception as e:  # the loop must never die silently
                logger.exception("online update cycle failed: %s",
                                 self._note_error(e))
                if self.metrics is not None:
                    self.metrics.observe_solve_failure()

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        self._wake.set()
        # detach under the lock, join OUTSIDE it: the loop thread takes
        # _state_lock (cycle counters, freezes), so joining while holding
        # it would deadlock — exactly what PH012 flags
        with self._state_lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout)
