"""Online learning tier: per-entity random-effect updates into the live
scorer, without a full refit or a full-model cutover.

The serving half (photon_ml_tpu/serving/) is read-only between hot swaps;
production GLMix freshness comes from cheap random-effect-only refits —
the per-entity subproblems are independent (the executor-sharding insight
of the source paper; arXiv 1611.02101, 1803.06333), so entities with new
feedback re-solve in milliseconds while the fixed effect stays frozen.
Three pieces:

  - `feedback.FeedbackBuffer` — bounded intake coalescing labeled
    observations per (coordinate, entity), backpressure -> Overloaded,
    per-entity dedup window.
  - `updater.OnlineUpdater` — background loop draining touched entities
    into the batched RE solver's padded pow-2 layout at micro-batch size,
    each entity's subproblem ANCHORED at its current coefficients
    (game/anchored.py: warm start + prior-pull regularization, so a few
    fresh rows refine rather than replace the batch solution); non-finite
    solves freeze the entity (never the live table); fault sites
    `online.solve` / `online.publish` retry transiently like chunk
    staging.
  - `delta.ModelDelta` — the changed rows of the stacked RE tables + a
    version vector; `ModelRegistry.apply_delta` scatters them into the
    device-resident tables under the registry lock (zero fresh XLA traces
    steady-state) and `rollback()` restores exact pre-delta rows.

Wire-up: `ScoringService(..., updates=OnlineUpdateConfig())` or
`cli.serve --enable-updates` (POST /feedback); staleness + update metrics
ride the serving `GET /metrics` surfaces; delta serialization lives in
models/io.py (`save_model_delta` / `load_model_delta`, durable writes).
"""
from photon_ml_tpu.online.delta import CoordinateDelta, ModelDelta  # noqa: F401
from photon_ml_tpu.online.feedback import (  # noqa: F401
    EntityFeedback, FeedbackBuffer, Observation,
)
from photon_ml_tpu.online.updater import (  # noqa: F401
    OnlineUpdateConfig, OnlineUpdater,
)
