"""Date-range input-path resolution.

Rebuild of the reference's date-partitioned input discovery:
  - DateRange.fromDates / fromDaysAgo ("yyyyMMdd-yyyyMMdd" and
    "START-END" days-ago specs, photon-lib/.../util/DateRange.scala:50-126)
  - IOUtils.getInputPathsWithinDateRange: <baseDir>/daily/YYYY/MM/DD per
    day in the range, skipping missing days, erroring when NONE exist
    (photon-client/.../util/IOUtils.scala:82-119)
  - GameDriver.pathsForDateRange: range and days-ago are mutually
    exclusive; neither means "use the base dirs as-is"
    (photon-client/.../cli/game/GameDriver.scala:103-126).
"""
from __future__ import annotations

import datetime
import os
from typing import List, Optional, Sequence


def parse_date_range(spec: str) -> tuple[datetime.date, datetime.date]:
    """'yyyyMMdd-yyyyMMdd' -> (start, end) inclusive."""
    try:
        start_s, end_s = spec.split("-")
        start = datetime.datetime.strptime(start_s, "%Y%m%d").date()
        end = datetime.datetime.strptime(end_s, "%Y%m%d").date()
    except ValueError as e:
        raise ValueError(
            f"date range {spec!r} is not 'yyyyMMdd-yyyyMMdd'") from e
    if end < start:
        raise ValueError(f"date range {spec!r} ends before it starts")
    return start, end


def parse_days_ago(spec: str,
                   today: Optional[datetime.date] = None
                   ) -> tuple[datetime.date, datetime.date]:
    """'START-END' days ago (e.g. '90-1') -> (start, end) dates."""
    today = today or datetime.date.today()
    try:
        start_ago, end_ago = (int(v) for v in spec.split("-"))
    except ValueError as e:
        raise ValueError(f"days-ago range {spec!r} is not 'START-END'") from e
    start = today - datetime.timedelta(days=start_ago)
    end = today - datetime.timedelta(days=end_ago)
    if end < start:
        raise ValueError(f"days-ago range {spec!r} ends before it starts")
    return start, end


def paths_for_date_range(
    base_dirs: str | Sequence[str],
    date_range: Optional[str] = None,
    days_ago: Optional[str] = None,
    today: Optional[datetime.date] = None,
) -> List[str]:
    """Expand base dirs to <base>/daily/YYYY/MM/DD day directories.

    Exactly the reference contract: both specs given is an error; neither
    returns the base dirs unchanged; missing day directories are skipped,
    but a range matching NO directory under a base dir raises."""
    if isinstance(base_dirs, (str, os.PathLike)):
        base_dirs = [str(base_dirs)]
    if date_range is not None and days_ago is not None:
        raise ValueError(
            "Both date range and days ago given. You must specify date "
            "ranges using only one format.")
    if date_range is None and days_ago is None:
        return list(base_dirs)
    start, end = (parse_date_range(date_range) if date_range is not None
                  else parse_days_ago(days_ago, today))
    out: List[str] = []
    for base in base_dirs:
        daily = os.path.join(base, "daily")
        found = []
        day = start
        while day <= end:
            p = os.path.join(daily, f"{day.year:04d}", f"{day.month:02d}",
                             f"{day.day:02d}")
            if os.path.isdir(p):
                found.append(p)
            day += datetime.timedelta(days=1)
        if not found:
            raise FileNotFoundError(
                f"No data folder found between {start} and {end} in {daily}")
        out.extend(found)
    return out
