from photon_ml_tpu.data.avro_game import (  # noqa: F401
    GameAvroResult, read_game_examples, write_game_examples,
)
from photon_ml_tpu.data.batching import (  # noqa: F401
    FixedEffectDataConfig, FixedEffectDataset, RandomEffectDataConfig,
    RandomEffectDataset, build_random_effect_dataset,
)
from photon_ml_tpu.data.game_data import (  # noqa: F401
    GameDataset, InputColumnNames, build_game_dataset,
)
from photon_ml_tpu.data.index_map import (  # noqa: F401
    DELIMITER, INTERCEPT_KEY, INTERCEPT_NAME, IndexMap, IndexMapCollection,
    build_index_map, feature_key,
)
from photon_ml_tpu.data.libsvm import read_libsvm  # noqa: F401
from photon_ml_tpu.data.samplers import (  # noqa: F401
    binary_classification_downsample, default_downsample, downsampler_for_task,
)
from photon_ml_tpu.data.stats import BasicStatisticalSummary  # noqa: F401
from photon_ml_tpu.data.streaming import (  # noqa: F401
    ChunkPlan, ChunkSpec, Prefetcher, StreamStats,
)
from photon_ml_tpu.data.validators import (  # noqa: F401
    DataValidationError, DataValidationType, validate_game_dataset,
)
