"""Out-of-core chunk streaming: host shards -> double-buffered device chunks.

The resident training path requires every coordinate's data on the
accelerator for the whole fit; bench config 5 documents that 5M MovieLens
rows exhaust HBM with all four coordinates resident.  Snap ML
(arXiv:1803.06333) and "Large-Scale Stochastic Learning using GPUs"
(arXiv:1702.07005) both recover near-resident throughput on datasets larger
than device memory with hierarchical memory management + pipelined
host<->accelerator chunk transfer.  This module is that layer:

  - `ChunkPlan` row-partitions a flat batch into power-of-two-sized chunks
    (via the ONE shape-bucketing rule, utils.math.ceil_pow2, shared with
    training prep and the serving micro-batcher) so the whole stream
    compiles at most two XLA programs: the full-chunk shape and the
    pow-2-padded tail shape.
  - `Prefetcher` double-buffers: a background thread stages chunk i+1
    (slice + pad + device transfer) while chunk i computes, with bounded
    lookahead so at most `depth` (default 2) chunks are device-resident.
  - `StreamStats` is the transfer-size accounting used where
    device.memory_stats() is unavailable (CPU tests, tunneled devices):
    peak resident chunk count/bytes and total bytes staged.

Nothing here is jax-traced: chunk STAGING is host work by design, and every
compiled consumer (ops/chunked.py) is keyed only on the chunk shape — chunk
COUNT never appears in a cache key, so growing the dataset re-uses every
program (tested by tests/test_streaming.py's compile-count regression).
"""
from __future__ import annotations

import dataclasses
import queue
import random
import threading
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.utils import faults, locktrace
from photon_ml_tpu.utils.math import ceil_pow2

# never plan chunks smaller than this: per-chunk dispatch overhead would
# dominate (over a tunneled device each program dispatch costs ~the floor
# bench.py measures via measure_dispatch_floor)
MIN_CHUNK_ROWS = 256

# staging retry policy: a flaky host read / device transfer must not kill an
# hours-long fit.  Transient failures (faults.is_transient: OSError,
# timeouts, injected TransientFault, ...) retry up to STAGE_MAX_ATTEMPTS
# with jittered exponential backoff; everything else — and always
# KeyboardInterrupt/SystemExit — propagates immediately.
STAGE_MAX_ATTEMPTS = 3
STAGE_BACKOFF_S = 0.05
STAGE_BACKOFF_JITTER = 0.5


class ChunkStagingError(RuntimeError):
    """A chunk failed to stage after exhausting its retry budget (or hit a
    fatal, non-retryable error).  The message names the chunk; the original
    failure rides as __cause__."""

    def __init__(self, message: str, chunk_index: int):
        super().__init__(message)
        self.chunk_index = chunk_index


@dataclasses.dataclass(frozen=True)
class ChunkSpec:
    """One row range [start, stop) padded to `padded_rows` (a power of two).
    Padding rows carry zero features / SAFE labels / zero weights and are
    excluded by the chunk mask."""

    index: int
    start: int
    stop: int
    padded_rows: int

    @property
    def rows(self) -> int:
        return self.stop - self.start


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """Static row partition of an [n, ...] batch into pow-2-sized chunks.

    All full chunks share one shape; the tail is padded to its own power of
    two, so a plan compiles at most TWO programs per consumer kernel
    regardless of how many chunks (i.e. how many rows) it covers."""

    num_rows: int
    chunk_rows: int                  # pow2 size of the full chunks
    chunks: Tuple[ChunkSpec, ...]

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)

    @property
    def chunk_shapes(self) -> Tuple[int, ...]:
        """Distinct padded sizes, ascending (<= 2 by construction)."""
        return tuple(sorted({c.padded_rows for c in self.chunks}))

    def chunk_bytes(self, bytes_per_row: int) -> int:
        """Device bytes of ONE full chunk (the double-buffer unit)."""
        return self.chunk_rows * bytes_per_row

    def process_block(self, spec: ChunkSpec, *, num_shards: int,
                      shard_lo: int, shard_hi: int) -> Tuple[int, int]:
        """The process-slice view of one chunk: padded-row offsets [lo, hi)
        of `spec` owned by data-axis shards [shard_lo, shard_hi) of
        `num_shards`.  On a multi-process mesh each process's devices hold
        a contiguous block of the data axis (parallel/mesh.py make_mesh),
        so its share of every chunk is the contiguous padded-row block
        returned here — the host then fetches/pads/transfers ONLY those
        rows (1/P of the stream per process, zero cross-host movement)."""
        if spec.padded_rows % num_shards:
            raise ValueError(
                f"chunk {spec.index} pads to {spec.padded_rows} rows, not a "
                f"multiple of {num_shards} data-axis shards; build the plan "
                "with row_multiple=num_shards")
        per = spec.padded_rows // num_shards
        return shard_lo * per, shard_hi * per

    @staticmethod
    def build(num_rows: int, *, chunk_rows: Optional[int] = None,
              hbm_budget_bytes: Optional[int] = None,
              bytes_per_row: Optional[int] = None,
              row_multiple: int = 1) -> "ChunkPlan":
        """Partition `num_rows` rows.

        Either pass `chunk_rows` (rounded up to a power of two) or a device
        budget: the chunk is then the largest power of two such that TWO
        chunks (current + prefetched) fit in `hbm_budget_bytes` given
        `bytes_per_row`.  A chunk covering every row degenerates to a
        single-chunk plan — the streamed oracle then matches the resident
        one bit-for-bit (tests rely on this).

        `row_multiple` additionally rounds every padded chunk size up to a
        multiple (the mesh data-axis size, so each staged chunk shards
        evenly over the devices).  The ≤2-compiled-shapes property is
        preserved: full chunks share one rounded size, the tail gets its
        own.
        """
        if num_rows < 1:
            raise ValueError(f"num_rows must be >= 1, got {num_rows}")
        if row_multiple < 1:
            raise ValueError(f"row_multiple must be >= 1, got {row_multiple}")
        if chunk_rows is None:
            if hbm_budget_bytes is None or bytes_per_row is None:
                raise ValueError("pass chunk_rows, or hbm_budget_bytes with "
                                 "bytes_per_row")
            per_chunk = max(hbm_budget_bytes // (2 * max(bytes_per_row, 1)), 1)
            chunk_rows = ceil_pow2(per_chunk)
            if chunk_rows > per_chunk:        # ceil overshot the budget
                chunk_rows //= 2
        mult = int(row_multiple)
        ceil_mult = lambda v: -(-int(v) // mult) * mult
        chunk_rows = int(ceil_pow2(max(int(chunk_rows), MIN_CHUNK_ROWS)))
        chunk_rows = min(chunk_rows, int(ceil_pow2(num_rows)))
        chunk_rows = ceil_mult(chunk_rows)
        chunks = []
        start = 0
        while start < num_rows:
            stop = min(start + chunk_rows, num_rows)
            rows = stop - start
            padded = (chunk_rows if rows == chunk_rows
                      else min(ceil_mult(ceil_pow2(rows)), chunk_rows))
            chunks.append(ChunkSpec(index=len(chunks), start=start, stop=stop,
                                    padded_rows=padded))
            start = stop
        return ChunkPlan(num_rows=num_rows, chunk_rows=chunk_rows,
                         chunks=tuple(chunks))


def pad_rows_host(a: np.ndarray, rows: int, fill) -> np.ndarray:
    """Host-side row pad of a [r, ...] slice to [rows, ...] with `fill`."""
    r = a.shape[0]
    if r == rows:
        return a
    out = np.full((rows,) + a.shape[1:], fill, a.dtype)
    out[:r] = a
    return out


class StreamStats:
    """Transfer-size accounting for one streaming consumer: the
    `memory_stats()` stand-in on backends that lack it (CPU, some tunneled
    devices).  `peak_resident_chunks` counts chunks simultaneously alive on
    device (staged or being consumed) — the double-buffer invariant is that
    it never exceeds the Prefetcher depth."""

    def __init__(self):
        self._lock = locktrace.tracked(threading.Lock(),
                                       "StreamStats._lock")
        self.total_bytes = 0
        self.chunks_staged = 0
        self.passes = 0
        self.resident_chunks = 0
        self.resident_bytes = 0
        self.peak_resident_chunks = 0
        self.peak_resident_bytes = 0
        # retry accounting: transient staging failures absorbed (retries)
        # and chunks that exhausted the retry budget (gave_up)
        self.retries = 0
        self.gave_up = 0
        # work-per-staged-byte accounting: chunk-epochs executed on
        # resident chunks (1 per chunk for a plain oracle pass, K per
        # chunk when the stochastic lane pins the chunk for K local
        # epochs) and examples processed (real rows x epochs).  The ratio
        # examples_processed / total_bytes is THE out-of-core efficiency
        # number — bench --stoch gates its improvement.
        self.local_epochs = 0
        self.examples_processed = 0

    def note_retry(self) -> None:
        with self._lock:
            self.retries += 1
        telemetry.counter("stream.retries").inc()

    def note_gave_up(self) -> None:
        with self._lock:
            self.gave_up += 1
        telemetry.counter("stream.gave_up").inc()

    def note_staged(self, nbytes: int) -> None:
        with self._lock:
            self.total_bytes += nbytes
            self.chunks_staged += 1
            self.resident_chunks += 1
            self.resident_bytes += nbytes
            self.peak_resident_chunks = max(self.peak_resident_chunks,
                                            self.resident_chunks)
            self.peak_resident_bytes = max(self.peak_resident_bytes,
                                           self.resident_bytes)
        # process-global mirror (telemetry.snapshot() aggregates every
        # Prefetcher; per-instance numbers stay on this object)
        telemetry.counter("stream.staged_bytes").inc(nbytes)
        telemetry.counter("stream.chunks_staged").inc()

    def note_released(self, nbytes: int) -> None:
        with self._lock:
            self.resident_chunks -= 1
            self.resident_bytes -= nbytes

    def note_pass(self) -> None:
        with self._lock:
            self.passes += 1

    def note_processed(self, rows: int, epochs: int = 1) -> None:
        """`epochs` chunk-epochs of consumer work on one resident chunk
        covering `rows` real (unpadded) rows."""
        with self._lock:
            self.local_epochs += epochs
            self.examples_processed += rows * epochs
        telemetry.counter("stream.local_epochs").inc(epochs)
        telemetry.counter("stream.examples").inc(rows * epochs)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            snap = {"total_bytes": self.total_bytes,
                    "chunks_staged": self.chunks_staged,
                    "passes": self.passes,
                    "peak_resident_chunks": self.peak_resident_chunks,
                    "peak_resident_bytes": self.peak_resident_bytes,
                    "retries": self.retries,
                    "gave_up": self.gave_up,
                    "local_epochs": self.local_epochs,
                    "examples_processed": self.examples_processed}
        snap["examples_per_staged_byte"] = (
            snap["examples_processed"] / snap["total_bytes"]
            if snap["total_bytes"] else 0.0)
        # metrics mirror: the ratio as a gauge so operators see
        # work-per-staged-byte without dividing counters themselves
        telemetry.gauge("stream.examples_per_staged_byte").set(
            snap["examples_per_staged_byte"])
        return snap


def _tree_device_put(host_tree):
    """Host pytree -> device, via jnp.asarray so dtypes canonicalize exactly
    as the resident path's transfers do (float64 host arrays become float32
    under the default config, float64 under x64)."""
    import jax
    return jax.tree_util.tree_map(
        lambda a: a if a is None else jnp.asarray(a), host_tree,
        is_leaf=lambda a: a is None)


def _tree_nbytes(dev_tree) -> int:
    """Bytes THIS process staged for a device chunk tree: on a
    multi-process mesh each chunk is a global array of which this process
    transferred only its addressable shards, so the accounting (and the
    warm-bytes gates built on it) stays per-process."""
    import jax

    from photon_ml_tpu.parallel import multihost
    return sum(multihost.local_nbytes(leaf)
               for leaf in jax.tree_util.tree_leaves(dev_tree)
               if leaf is not None)


_DONE = object()


class Prefetcher:
    """Double-buffered host->device chunk pipeline over one ChunkPlan.

    `fetch(spec)` returns the chunk's HOST pytree (sliced + padded numpy
    arrays); a background thread runs fetch + device transfer for chunk
    i+1 while the consumer computes on chunk i.  Lookahead is bounded by a
    semaphore so at most `depth` chunks are device-resident at once —
    depth=2 is the classic double buffer.  Each `stream()` call is one full
    pass (one value/gradient evaluation); the thread dies with the pass.

    Failure containment: TRANSIENT staging errors (faults.is_transient —
    OSError/timeouts/injected TransientFault) retry up to `max_attempts`
    with jittered exponential backoff (StreamStats counts the retries);
    a chunk that exhausts its budget raises ChunkStagingError naming the
    chunk in the consumer.  Fatal errors skip the retry loop entirely, and
    KeyboardInterrupt/SystemExit re-raise AS THEMSELVES in the consumer —
    an operator interrupt must never be laundered into a staging error."""

    def __init__(self, plan: ChunkPlan, fetch: Callable[[ChunkSpec], object],
                 depth: int = 2, stats: Optional[StreamStats] = None,
                 max_attempts: int = STAGE_MAX_ATTEMPTS,
                 backoff_s: float = STAGE_BACKOFF_S,
                 transfer: Optional[Callable[[object], object]] = None):
        if depth < 2:
            # the producer stages chunk k only after the consumer has taken
            # chunk k-depth+1, so depth 1 would deadlock before chunk 0
            raise ValueError(f"depth must be >= 2, got {depth}")
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.plan = plan
        self.fetch = fetch
        self.depth = depth
        self.max_attempts = max_attempts
        self.backoff_s = backoff_s
        self.stats = stats if stats is not None else StreamStats()
        # host pytree -> device placement, called as transfer(host, spec);
        # the default is an unsharded jnp.asarray transfer — mesh consumers
        # (ops/chunked.py) pass a data-sharded device_put so each chunk
        # lands split over the mesh (and, multi-process, assembled from
        # this process's row block alone)
        self._transfer = (transfer if transfer is not None
                          else lambda host, spec: _tree_device_put(host))

    def _stage_with_retry(self, spec: ChunkSpec, jitter: random.Random):
        """fetch + device transfer for one chunk, absorbing transient
        failures up to the attempt budget."""
        attempt = 0
        while True:
            attempt += 1
            try:
                faults.fire("stage.fetch", chunk=spec.index)
                host = self.fetch(spec)
                faults.fire("stage.transfer", chunk=spec.index)
                return self._transfer(host, spec)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                if not faults.is_transient(e):
                    self.stats.note_gave_up()
                    raise ChunkStagingError(
                        f"chunk staging failed for chunk {spec.index} of "
                        f"{self.plan.num_chunks} (fatal "
                        f"{type(e).__name__}, not retryable)",
                        spec.index) from e
                if attempt >= self.max_attempts:
                    self.stats.note_gave_up()
                    raise ChunkStagingError(
                        f"chunk staging failed for chunk {spec.index} of "
                        f"{self.plan.num_chunks} after {attempt} "
                        f"attempt(s)", spec.index) from e
                self.stats.note_retry()
                telemetry.event("stage_retry", chunk=spec.index,
                                attempt=attempt,
                                error=f"{type(e).__name__}: {e}")
                # exponential backoff with jitter so concurrent streams
                # don't re-hammer a struggling source in lockstep
                delay = (self.backoff_s * (2 ** (attempt - 1))
                         * (1.0 + STAGE_BACKOFF_JITTER * jitter.random()))
                time.sleep(delay)

    def stream(self, pin_epochs: int = 1
               ) -> Iterator[Tuple[ChunkSpec, object]]:
        """One full pass over the plan's chunks.

        `pin_epochs` declares how many local epochs the CONSUMER will run
        on each yielded chunk before asking for the next one (the
        stochastic lane, optim/stochastic.py).  The chunk is staged ONCE
        and stays pinned on device for all of them — it never round-trips
        back through the queue — while the producer keeps prefetching the
        next chunk behind it (the double-buffer bound is unchanged: at
        most `depth` chunks resident).  StreamStats accounts the extra
        work: `local_epochs` += pin_epochs and `examples_processed` +=
        rows * pin_epochs per chunk, which is what moves
        examples_per_staged_byte."""
        if pin_epochs < 1:
            raise ValueError(f"pin_epochs must be >= 1, got {pin_epochs}")
        self.stats.note_pass()
        lookahead = threading.Semaphore(self.depth - 1)
        q: "queue.Queue" = queue.Queue()
        cancel = threading.Event()
        # deterministic per-pass jitter (seeded by the pass ordinal) keeps
        # retry timing reproducible for a given plan + failure sequence
        jitter = random.Random(self.stats.passes)

        def producer():
            spec = None
            try:
                for spec in self.plan.chunks:
                    # token acquired BEFORE staging: the device never holds
                    # more than `depth` chunks, counting the one the
                    # consumer is computing on
                    while not lookahead.acquire(timeout=0.1):
                        if cancel.is_set():
                            return
                    if cancel.is_set():
                        return
                    # span on the PREFETCH thread: staging gets its own
                    # track in the trace, overlapping the consumer's solve
                    with telemetry.span("stage", chunk=spec.index,
                                        rows=spec.rows):
                        dev = self._stage_with_retry(spec, jitter)
                    self.stats.note_staged(_tree_nbytes(dev))
                    q.put((spec, dev))
                q.put(_DONE)
            except (KeyboardInterrupt, SystemExit) as e:
                # NOT a staging failure: re-raise distinctly in the
                # consumer (the operator interrupted / the process is
                # exiting), never wrapped into a RuntimeError
                q.put(("interrupt", e))
            except ChunkStagingError as e:  # already named + chained
                q.put(e)
            except BaseException as e:  # unexpected: name the chunk anyway
                idx = spec.index if spec is not None else -1
                err = ChunkStagingError(
                    f"chunk staging failed for chunk {idx} of "
                    f"{self.plan.num_chunks}", max(idx, 0))
                err.__cause__ = e
                q.put(err)

        thread = threading.Thread(target=producer, daemon=True,
                                  name="photon-chunk-prefetch")
        thread.start()
        prev_bytes = 0
        try:
            while True:
                item = q.get()
                if item is _DONE:
                    return
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == "interrupt":
                    raise item[1]
                if isinstance(item, BaseException):
                    raise item
                spec, dev = item
                if prev_bytes:
                    # the consumer asked for chunk i+1 => it has dispatched
                    # all work on chunk i and dropped its reference
                    self.stats.note_released(prev_bytes)
                prev_bytes = _tree_nbytes(dev)
                lookahead.release()
                self.stats.note_processed(spec.rows, pin_epochs)
                yield spec, dev
                dev = None
        finally:
            cancel.set()
            if prev_bytes:
                self.stats.note_released(prev_bytes)
