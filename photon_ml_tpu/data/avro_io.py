"""Reference-compatible Avro I/O: training data, models, scores.

Schema-compatible with the reference's photon-avro-schemas
(photon-avro-schemas/src/main/avro/*.avsc) so data and models interchange
with the Spark implementation:
  - TrainingExampleAvro + FeatureAvro  (read path of AvroDataReader,
    photon-client/.../data/avro/AvroDataReader.scala:53-451)
  - BayesianLinearModelAvro + NameTermValueAvro  (model save/load of
    ModelProcessingUtils.scala:58-669)
  - ScoringResultAvro  (ScoreProcessingUtils.scala)
  - LatentFactorAvro   (matrix factorization save/load)

The reference reads feature bags per shard and merges them
(AvroDataReader.readMerged); here one bag per file is read into a dense
[n, d] shard via an IndexMap (sparse BCOO assembly is a dataset-build
option at the call site).
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.avro_codec import read_container, write_container
from photon_ml_tpu.data.index_map import (
    DELIMITER, INTERCEPT_KEY, IndexMap, build_index_map, feature_key,
)

_NS = "com.linkedin.photon.avro.generated"

FEATURE_AVRO = {"name": "FeatureAvro", "namespace": _NS, "type": "record",
                "fields": [{"name": "name", "type": "string"},
                           {"name": "term", "type": "string"},
                           {"name": "value", "type": "double"}]}

TRAINING_EXAMPLE_AVRO = {
    "name": "TrainingExampleAvro", "namespace": _NS, "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ]}

NAME_TERM_VALUE_AVRO = {"name": "NameTermValueAvro", "namespace": _NS,
                        "type": "record",
                        "fields": [{"name": "name", "type": "string"},
                                   {"name": "term", "type": "string"},
                                   {"name": "value", "type": "double"}]}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "name": "BayesianLinearModelAvro", "namespace": _NS, "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ]}

SCORING_RESULT_AVRO = {
    "name": "ScoringResultAvro", "namespace": _NS, "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ]}

LATENT_FACTOR_AVRO = {
    "name": "LatentFactorAvro", "namespace": _NS, "type": "record",
    "fields": [{"name": "effectId", "type": "string"},
               {"name": "latentFactor",
                "type": {"type": "array", "items": "double"}}]}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "name": "FeatureSummarizationResultAvro", "namespace": _NS,
    "type": "record",
    "fields": [{"name": "featureName", "type": "string"},
               {"name": "featureTerm", "type": "string"},
               {"name": "metrics",
                "type": {"type": "map", "values": "double"}}]}


# -- training data -----------------------------------------------------------


def write_training_examples(
    path: str,
    x: np.ndarray,
    y: np.ndarray,
    index_map: IndexMap,
    weights: Optional[np.ndarray] = None,
    offsets: Optional[np.ndarray] = None,
    uids: Optional[List[str]] = None,
    metadata: Optional[List[Dict[str, str]]] = None,
) -> None:
    """Dense [n, d] (intercept column skipped) -> TrainingExampleAvro file."""
    intercept = index_map.intercept_index

    def gen():
        for i in range(x.shape[0]):
            feats = []
            row = x[i]
            for j in np.nonzero(row)[0]:
                if intercept is not None and j == intercept:
                    continue
                name, term = index_map.name_term(int(j))
                feats.append({"name": name, "term": term, "value": float(row[j])})
            yield {"uid": uids[i] if uids else None,
                   "label": float(y[i]), "features": feats,
                   "metadataMap": metadata[i] if metadata else None,
                   "weight": None if weights is None else float(weights[i]),
                   "offset": None if offsets is None else float(offsets[i])}

    write_container(path, TRAINING_EXAMPLE_AVRO, gen())


def _read_training_examples_native(paths, index_map):
    """Columnar fast path over the native block decoder; None -> fall back."""
    from photon_ml_tpu.data import avro_native
    required = ("label", "features#count", "features.name", "features.term",
                "features.value", "uid#present", "uid", "weight#present",
                "weight", "offset#present", "offset")
    cols_list = []
    for p in paths:
        cols = avro_native.read_columnar(p)
        if cols is None or any(k not in cols for k in required):
            # unsupported schema shape OR a schema variant missing optional
            # fields -> pure-Python path (which tolerates absent fields)
            return None
        cols_list.append(cols)

    y = np.concatenate([c["label"] for c in cols_list])
    n = len(y)
    counts = np.concatenate([c["features#count"] for c in cols_list])
    values = np.concatenate([c["features.value"] for c in cols_list])
    # vectorized (name, term) -> index resolution; Python touches only the
    # VOCABULARY, never the occurrence stream (avro_native.py helper)
    from photon_ml_tpu.data.avro_native import resolve_feature_keys
    index_map, col_idx = resolve_feature_keys(
        [c["features.name"] for c in cols_list],
        [c["features.term"] for c in cols_list], index_map)
    row_idx = np.repeat(np.arange(n), counts)

    x = np.zeros((n, index_map.size))
    valid = col_idx >= 0
    x[row_idx[valid], col_idx[valid]] = values[valid]
    if index_map.intercept_index is not None:
        x[:, index_map.intercept_index] = 1.0

    def opt_f64(key, default):
        present = np.concatenate([c[f"{key}#present"] for c in cols_list])
        vals = np.concatenate([c[key] for c in cols_list])
        return bool(present.any()), np.where(present == 1, vals, default)

    any_w, weights = opt_f64("weight", 1.0)
    any_o, offsets = opt_f64("offset", 0.0)
    uid_present = np.concatenate([c["uid#present"] for c in cols_list])
    uid_strs: List[str] = []
    for c in cols_list:
        uid_strs.extend(c["uid"].to_list())
    uids = [s if p else None for s, p in zip(uid_strs, uid_present)]
    return (x, y, weights if any_w else None, offsets if any_o else None,
            uids, index_map)


def read_training_examples(
    paths: str | Iterable[str],
    index_map: Optional[IndexMap] = None,
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], Optional[np.ndarray],
           List[Optional[str]], IndexMap]:
    """TrainingExampleAvro file(s) -> (x, y, weights, offsets, uids, index_map).

    Two-pass like the reference FeatureIndexingJob + AvroDataReader: build
    the (name, term) index map first (unless given), then fill the dense
    matrix with the intercept column appended last.  Decode runs through the
    native block decoder (data/avro_native.py) when available, falling back
    to the pure-Python codec."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    paths = list(paths)
    fast = _read_training_examples_native(paths, index_map)
    if fast is not None:
        return fast
    if index_map is None:
        names = []
        for p in paths:
            for rec in read_container(p):
                names.extend((f["name"], f["term"]) for f in rec["features"])
        index_map = build_index_map(names, add_intercept=True)

    rows = []
    for p in paths:
        rows.extend(read_container(p))
    n, d = len(rows), index_map.size
    x = np.zeros((n, d))
    y = np.zeros(n)
    weights = np.ones(n)
    offsets = np.zeros(n)
    any_w = any_o = False
    uids: List[Optional[str]] = []
    intercept = index_map.intercept_index
    for i, rec in enumerate(rows):
        y[i] = rec["label"]
        uids.append(rec.get("uid"))
        if rec.get("weight") is not None:
            weights[i] = rec["weight"]; any_w = True
        if rec.get("offset") is not None:
            offsets[i] = rec["offset"]; any_o = True
        for f in rec["features"]:
            j = index_map.index_of(f["name"], f["term"])
            if j >= 0:
                x[i, j] = f["value"]
        if intercept is not None:
            x[i, intercept] = 1.0
    return (x, y, weights if any_w else None, offsets if any_o else None,
            uids, index_map)


# -- models ------------------------------------------------------------------

_MODEL_CLASS = {
    "logistic_regression":
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    "linear_regression":
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    "poisson_regression":
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    "smoothed_hinge_loss_linear_svm":
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_TASK_BY_CLASS = {v: k for k, v in _MODEL_CLASS.items()}


def write_glm_avro(path: str, model_id: str, task_type: str,
                   means: np.ndarray, index_map: IndexMap,
                   variances: Optional[np.ndarray] = None) -> None:
    """One GLM -> BayesianLinearModelAvro record (reference:
    ModelProcessingUtils + AvroUtils.convertGLMModelToBayesianLinearModelAvro)."""
    def ntv(vec):
        out = []
        for j in np.nonzero(np.asarray(vec))[0]:
            name, term = index_map.name_term(int(j))
            out.append({"name": name, "term": term, "value": float(vec[j])})
        return out

    rec = {"modelId": model_id, "modelClass": _MODEL_CLASS.get(task_type),
           "means": ntv(means),
           "variances": None if variances is None else ntv(variances),
           "lossFunction": None}
    write_container(path, BAYESIAN_LINEAR_MODEL_AVRO, [rec])


def _read_model_records(path_or_paths):
    """BayesianLinearModelAvro records from one container file or, for the
    reference's partitioned layout, a list of part files concatenated in
    order."""
    if isinstance(path_or_paths, (list, tuple)):
        recs = []
        for p in path_or_paths:
            recs.extend(read_container(p))
        return recs
    return list(read_container(path_or_paths))


def model_record_keys(recs) -> List[Tuple[str, str]]:
    """All (name, term) feature keys appearing in a batch of
    BayesianLinearModelAvro records (means + variances)."""
    keys = []
    for rec in recs:
        keys.extend((f["name"], f["term"]) for f in rec["means"])
        keys.extend((f["name"], f["term"])
                    for f in rec.get("variances") or ())
    return keys


def glm_arrays_from_record(rec, index_map: IndexMap
                           ) -> Tuple[str, Optional[str], np.ndarray,
                                      Optional[np.ndarray]]:
    """One BayesianLinearModelAvro record -> (model_id, task, means,
    variances) dense in `index_map`'s column order."""
    means = np.zeros(index_map.size)
    for f in rec["means"]:
        j = index_map.index_of(f["name"], f["term"])
        if j >= 0:
            means[j] = f["value"]
    variances = None
    if rec.get("variances"):
        variances = np.zeros(index_map.size)
        for f in rec["variances"]:
            j = index_map.index_of(f["name"], f["term"])
            if j >= 0:
                variances[j] = f["value"]
    task = _TASK_BY_CLASS.get(rec.get("modelClass") or "", None)
    return rec["modelId"], task, means, variances


def read_glm_avro(path, index_map: Optional[IndexMap] = None
                  ) -> Tuple[str, Optional[str], np.ndarray,
                             Optional[np.ndarray], IndexMap]:
    """-> (model_id, task_type, means, variances, index_map)."""
    recs = _read_model_records(path)
    if len(recs) != 1:
        raise ValueError(f"{path}: expected 1 model record, got {len(recs)}")
    rec = recs[0]
    if index_map is None:
        # means AND variances: an L1-zeroed coefficient can still carry a
        # nonzero posterior variance entry
        index_map = build_index_map(model_record_keys(recs),
                                    add_intercept=True)
    model_id, task, means, variances = glm_arrays_from_record(rec, index_map)
    return model_id, task, means, variances, index_map


def write_random_effect_avro(path: str, task_type: str,
                             entity_ids, coefficients: np.ndarray,
                             index_map: IndexMap,
                             projection: Optional[np.ndarray] = None,
                             variances: Optional[np.ndarray] = None) -> None:
    """Per-entity GLMs -> one container of BayesianLinearModelAvro records
    (modelId = entity id), always in ORIGINAL feature space — the reference
    stores random-effect models per entity under random-effect/<coord>/
    (ModelProcessingUtils.scala:71-135) with name.term feature keys.

    `coefficients` is [E, d_local]; `projection` (optional, [E, d_local])
    maps local slots to global columns (-1 = padding), exactly the
    RandomEffectModel layout, so projected models export without
    materializing [E, d_global]."""
    coefficients = np.asarray(coefficients)
    variances = None if variances is None else np.asarray(variances)

    def ntv_entity(vec, e):
        out = []
        for j in np.nonzero(vec)[0]:
            g = int(j) if projection is None else int(projection[e, j])
            if g < 0:
                continue
            name, term = index_map.name_term(g)
            out.append({"name": name, "term": term, "value": float(vec[j])})
        return out

    def gen():
        for e, eid in enumerate(np.asarray(entity_ids)):
            yield {"modelId": str(eid),
                   "modelClass": _MODEL_CLASS.get(task_type),
                   "means": ntv_entity(coefficients[e], e),
                   "variances": (None if variances is None
                                 else ntv_entity(variances[e], e)),
                   "lossFunction": None}

    write_container(path, BAYESIAN_LINEAR_MODEL_AVRO, gen())


def read_random_effect_avro(path, index_map: Optional[IndexMap] = None
                            ) -> Tuple[List[str], np.ndarray,
                                       Optional[np.ndarray], IndexMap]:
    """-> (entity_ids, means [E, d], variances or None, index_map); models
    come back dense in ORIGINAL space (projection is a training-time
    artifact, reference loads are original-space too).  `path` may be a
    list of part files (reference partitioned layout)."""
    recs = _read_model_records(path)
    if index_map is None:
        index_map = build_index_map(model_record_keys(recs),
                                    add_intercept=True)
    return re_arrays_from_records(recs, index_map) + (index_map,)


def re_arrays_from_records(recs, index_map: IndexMap
                           ) -> Tuple[List[str], np.ndarray,
                                      Optional[np.ndarray]]:
    """Per-entity BayesianLinearModelAvro records -> (entity_ids,
    means [E, d], variances or None) dense in `index_map`'s order."""
    e_ids = [rec["modelId"] for rec in recs]
    d = index_map.size
    means = np.zeros((len(recs), d))
    any_var = any(rec.get("variances") for rec in recs)
    variances = np.zeros((len(recs), d)) if any_var else None
    for e, rec in enumerate(recs):
        for f in rec["means"]:
            j = index_map.index_of(f["name"], f["term"])
            if j >= 0:
                means[e, j] = f["value"]
        if any_var:
            for f in rec.get("variances") or ():
                j = index_map.index_of(f["name"], f["term"])
                if j >= 0:
                    variances[e, j] = f["value"]
    return e_ids, means, variances


# -- scores ------------------------------------------------------------------


def write_feature_stats_avro(path: str, summary, index_map: IndexMap) -> None:
    """Per-feature statistics -> FeatureSummarizationResultAvro records
    (reference: ModelProcessingUtils.writeBasicStatistics, scala:560-630 —
    one record per feature with the same metric-map keys)."""
    mean_abs = summary.mean_abs

    def gen():
        for j in range(index_map.size):
            name, term = index_map.name_term(j)
            yield {"featureName": name, "featureTerm": term,
                   "metrics": {"max": float(summary.max[j]),
                               "min": float(summary.min[j]),
                               "mean": float(summary.mean[j]),
                               "normL1": float(summary.norm_l1[j]),
                               "normL2": float(summary.norm_l2[j]),
                               "numNonzeros": float(summary.num_nonzeros[j]),
                               "variance": float(summary.variance[j]),
                               "meanAbs": float(mean_abs[j])}}

    write_container(path, FEATURE_SUMMARIZATION_RESULT_AVRO, gen())


def read_feature_stats_avro(path: str):
    """-> list of (name, term, metrics-dict), record order preserved."""
    return [(r["featureName"], r["featureTerm"], dict(r["metrics"]))
            for r in read_container(path)]


def write_scores_avro(path: str, model_id: str, scores: np.ndarray,
                      labels: Optional[np.ndarray] = None,
                      weights: Optional[np.ndarray] = None,
                      uids: Optional[List[Optional[str]]] = None) -> None:
    """reference: ScoreProcessingUtils.saveScoredItemsToHDFS."""
    def gen():
        for i, s in enumerate(np.asarray(scores)):
            yield {"uid": uids[i] if uids else None,
                   "label": None if labels is None else float(labels[i]),
                   "modelId": model_id, "predictionScore": float(s),
                   "weight": None if weights is None else float(weights[i]),
                   "metadataMap": None}
    write_container(path, SCORING_RESULT_AVRO, gen())


def read_scores_avro(path: str):
    recs = list(read_container(path))
    scores = np.asarray([r["predictionScore"] for r in recs])
    labels = np.asarray([r["label"] if r["label"] is not None else np.nan
                         for r in recs])
    return scores, labels, recs


# -- latent factors (matrix factorization) -----------------------------------


def write_latent_factors_avro(path: str, ids: Iterable[str],
                              factors: np.ndarray) -> None:
    write_container(path, LATENT_FACTOR_AVRO,
                    ({"effectId": str(i), "latentFactor": list(map(float, f))}
                     for i, f in zip(ids, np.asarray(factors))))


def read_latent_factors_avro(path: str) -> Tuple[List[str], np.ndarray]:
    recs = list(read_container(path))
    return ([r["effectId"] for r in recs],
            np.asarray([r["latentFactor"] for r in recs]))
