"""Input-data sanity checks, gated by validation intensity.

Rebuild of photon-client/.../data/DataValidators.scala:33-332 and
DataValidationType: per-task row checks (finite features/offset/weight for
every task; finite label for linear/Poisson; binary label for logistic and
smoothed hinge; non-negative label for Poisson), run over the FULL dataset, a
10% SAMPLE, or DISABLED.

TPU-first divergence from the reference: the reference folds a per-row
predicate over the RDD and can only report *that* a check failed; here the
checks are vectorized numpy reductions over the struct-of-arrays GameDataset,
which is both orders of magnitude faster host-side and lets the error name
the first offending row (and feature column for feature checks).
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset


class DataValidationType(str, enum.Enum):
    """reference: DataValidationType.scala (VALIDATE_FULL/SAMPLE/DISABLED)."""

    VALIDATE_FULL = "full"
    VALIDATE_SAMPLE = "sample"
    VALIDATE_DISABLED = "disabled"


SAMPLE_FRACTION = 0.10  # reference: sanityCheckData sample(fraction = 0.10)


class DataValidationError(ValueError):
    """Validation failure; message names every failed check with the first
    offending row (reference raises IllegalArgumentException with the
    aggregated message list, DataValidators.scala:244-247)."""


def _first_bad(mask: np.ndarray) -> int:
    return int(np.argmax(mask))


def _positive_weight_errors(dataset: GameDataset) -> List[str]:
    """'Verify and reject' non-positive sample weights, like the GAME
    driver's checkData (reference: cli/game/training/Driver.scala:215-240
    — "Found N data points with weights <= 0. Please fix data set.").
    Always counts the FULL array: the 1-D scan is cheap and a sampled
    count would understate the problem."""
    if dataset.weights is None:
        return []
    w = np.asarray(dataset.weights)
    nonpos = np.isfinite(w) & (w <= 0.0)
    if not nonpos.any():
        return []
    return [f"Found {int(nonpos.sum())} data points with weights <= 0 "
            f"(first at row {_first_bad(nonpos)}). Please fix data set."]


def _check_positive_weights(dataset: GameDataset) -> None:
    errors = _positive_weight_errors(dataset)
    if errors:
        raise DataValidationError(
            "Data Validation failed:\n" + "\n".join(errors))


def _check_label(task_type: str, y: np.ndarray, rows: np.ndarray) -> List[str]:
    errors = []
    if task_type in ("logistic_regression", "smoothed_hinge_loss_linear_svm"):
        bad = ~((y == 0.0) | (y == 1.0))
        if bad.any():
            i = _first_bad(bad)
            errors.append(
                f"Data contains row(s) with non-binary label(s): first at row "
                f"{int(rows[i])} (label={y[i]!r})")
    else:
        bad = ~np.isfinite(y)
        if bad.any():
            i = _first_bad(bad)
            errors.append(
                f"Data contains row(s) with non-finite label(s): first at row "
                f"{int(rows[i])} (label={y[i]!r})")
        if task_type == "poisson_regression":
            bad = np.isfinite(y) & (y < 0)
            if bad.any():
                i = _first_bad(bad)
                errors.append(
                    f"Data contains row(s) with negative label(s): first at "
                    f"row {int(rows[i])} (label={y[i]!r})")
    return errors


def validate_game_dataset(
    dataset: GameDataset,
    task_type: str,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
    seed: int = 0,
    check_weights: bool = True,
) -> None:
    """Raise DataValidationError naming every failed check, or return None.

    reference: DataValidators.sanityCheckData / sanityCheckDataFrameForTraining
    (task dispatch at DataValidators.scala:221-229, gating at 231-247).
    """
    validation_type = DataValidationType(validation_type)
    if validation_type is DataValidationType.VALIDATE_DISABLED:
        # the weights <= 0 rejection still runs by default: the reference
        # gates its checkData on a SEPARATE on-by-default flag, not on
        # validation intensity (cli/game/training/Driver.scala:215-240,
        # GameTrainingParams checkData) — and like that flag it has its own
        # opt-out (`check_weights=False` / CLI --no-weight-check)
        if check_weights:
            _check_positive_weights(dataset)
        return
    n = dataset.num_rows
    if validation_type is DataValidationType.VALIDATE_SAMPLE:
        rng = np.random.default_rng(seed)
        rows = np.flatnonzero(rng.random(n) < SAMPLE_FRACTION)
        if len(rows) == 0:
            rows = np.arange(n)
        take = lambda a: np.asarray(a)[rows]
    else:
        # FULL: reduce over the arrays in place — fancy-indexing with
        # arange(n) would copy every (possibly multi-GB) shard
        rows = np.arange(n)
        take = np.asarray

    errors: List[str] = []
    errors.extend(_check_label(task_type, take(dataset.response), rows))
    from photon_ml_tpu.data.game_data import _is_sparse
    for shard, x in dataset.feature_shards.items():
        if _is_sparse(x):
            # sparse shard (wide-FE path): validate the STORED values; the
            # implicit zeros are finite by construction.  Row slice first
            # under SAMPLE so the check stays proportional; the COO copy is
            # built only to NAME the offending row/column once a non-finite
            # value is known to exist.
            xs = (x.tocsr()[rows]
                  if validation_type is DataValidationType.VALIDATE_SAMPLE
                  else x)
            if not np.isfinite(xs.data).all():
                coo = xs.tocoo()
                i = _first_bad(~np.isfinite(coo.data))
                errors.append(
                    f"Data contains row(s) with non-finite feature(s): first "
                    f"at row {int(rows[coo.row[i]])}, shard {shard!r} column "
                    f"{int(coo.col[i])}")
            continue
        vals = take(x)
        if not np.isfinite(vals).all():
            bad_rows, bad_cols = np.nonzero(~np.isfinite(vals))
            errors.append(
                f"Data contains row(s) with non-finite feature(s): first at "
                f"row {int(rows[bad_rows[0]])}, shard {shard!r} column "
                f"{int(bad_cols[0])}")
    for name, arr in (("offset", dataset.offsets), ("weight", dataset.weights)):
        if arr is None:
            continue
        vals = take(arr)
        bad = ~np.isfinite(vals)
        if bad.any():
            i = _first_bad(bad)
            errors.append(
                f"Data contains row(s) with non-finite {name}(s): first at "
                f"row {int(rows[i])} ({name}={vals[i]!r})")
    if check_weights:
        errors.extend(_positive_weight_errors(dataset))
    if errors:
        raise DataValidationError(
            "Data Validation failed:\n" + "\n".join(errors))
