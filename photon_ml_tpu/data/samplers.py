"""Down-samplers for fixed-effect updates.

reference: photon-lib/.../sampler/{DownSampler,BinaryClassificationDownSampler,
DefaultDownSampler}.scala:33-69, applied per fixed-effect update at
DistributedOptimizationProblem.runWithSampling:143.

TPU design (SURVEY §2.14 P6): no data movement — down-sampling is a weight
mask computed from a PRNG key.  Kept negatives get weight / rate so the
gradient stays unbiased, exactly the reference's rescale.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def binary_classification_downsample(
    key: jax.Array,
    labels: jax.Array,
    weights: Optional[jax.Array],
    rate: float,
) -> Tuple[jax.Array, jax.Array]:
    """Keep all positives; keep negatives w.p. `rate` with weight 1/rate.

    Returns (mask, weights).  reference:
    BinaryClassificationDownSampler.scala:47-68."""
    if not 0.0 < rate < 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1), got {rate}")
    w = jnp.ones_like(labels) if weights is None else weights
    u = jax.random.uniform(key, labels.shape, dtype=labels.dtype)
    is_pos = labels > 0.5
    keep = is_pos | (u < rate)
    new_w = jnp.where(is_pos, w, w / rate)
    return keep.astype(labels.dtype), new_w


def default_downsample(
    key: jax.Array,
    labels: jax.Array,
    weights: Optional[jax.Array],
    rate: float,
) -> Tuple[jax.Array, jax.Array]:
    """Uniform row sampling with 1/rate weight rescale (regression tasks).
    reference: DefaultDownSampler.scala."""
    if not 0.0 < rate < 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1), got {rate}")
    w = jnp.ones_like(labels) if weights is None else weights
    u = jax.random.uniform(key, labels.shape, dtype=labels.dtype)
    keep = u < rate
    return keep.astype(labels.dtype), w / rate


def downsampler_for_task(task_type: str):
    """reference: DownSampler factory choice in DistributedOptimizationProblem."""
    if task_type in ("logistic_regression", "smoothed_hinge_loss_linear_svm"):
        return binary_classification_downsample
    return default_downsample
