"""Schema-compiled native Avro decode: Python compiler + ctypes bindings.

VERDICT r2 item 9: the per-record pure-Python codec is the ingest
bottleneck for corpus-scale files (the role of the reference's
AvroDataReader on Spark executors, AvroDataReader.scala:53-451).  Here a
record schema is compiled once into a flat int32 op program, and the C
interpreter (photon_ml_tpu/native/avro_decode.c) executes it per record
over each decompressed container block, appending leaf values into typed
columns — one C loop instead of one Python decode call per record.

Columns come back as numpy arrays keyed by field path:
  "label" -> float64 [n];  "uid" -> StrColumn;  "uid#present" -> int64 [n]
  "features#count" -> int64 [n];  "features.name" -> StrColumn (flattened)

Unsupported schema shapes (unions beyond [null, X], maps with non-string
values, fixed) make `compile_schema` return None and callers fall back to
the pure-Python codec — behavior, not availability, is the contract.
"""
from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.avro_codec import iter_raw_blocks

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "avro_decode.c")
_SO = os.path.join(_NATIVE_DIR, "libavrodec.so")

OP_LONG, OP_DOUBLE, OP_FLOAT, OP_BOOL, OP_STRING, OP_ENUM, OP_OPT, \
    OP_ARRAY, OP_MAP_SKIP, OP_MAP = range(10)
KIND_I64, KIND_F64, KIND_STR = range(3)

_PRIMITIVE_OPS = {"long": (OP_LONG, KIND_I64), "int": (OP_LONG, KIND_I64),
                  "double": (OP_DOUBLE, KIND_F64),
                  "float": (OP_FLOAT, KIND_F64),
                  "boolean": (OP_BOOL, KIND_I64),
                  "string": (OP_STRING, KIND_STR),
                  "bytes": (OP_STRING, KIND_STR)}

_lib = None
_lib_failed = False


def _load_lib():
    """Compile (if stale) and load the shared library; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            subprocess.run(["cc", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO],
                           check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(_SO)
        lib.avrodec_decode_block.restype = ctypes.c_int64
        lib.avrodec_decode_block.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int32]
        lib.avrodec_alloc_cols.restype = ctypes.c_void_p
        lib.avrodec_alloc_cols.argtypes = [ctypes.c_int32,
                                           ctypes.POINTER(ctypes.c_int32)]
        lib.avrodec_free_cols.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        for name, restype in (("avrodec_col_len", ctypes.c_int64),
                              ("avrodec_col_blob_len", ctypes.c_int64),
                              ("avrodec_col_i64", ctypes.POINTER(ctypes.c_int64)),
                              ("avrodec_col_f64", ctypes.POINTER(ctypes.c_double)),
                              ("avrodec_col_blob", ctypes.POINTER(ctypes.c_uint8))):
            fn = getattr(lib, name)
            fn.restype = restype
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


@dataclasses.dataclass
class StrColumn:
    """Flattened UTF-8 column: `offsets[i]` is the END byte offset of
    element i in `blob` (start = offsets[i-1] or 0)."""

    offsets: np.ndarray  # int64 [n]
    blob: bytes

    def __len__(self) -> int:
        return len(self.offsets)

    def to_list(self) -> List[str]:
        out, start = [], 0
        b = self.blob
        for end in self.offsets.tolist():
            out.append(b[start:end].decode("utf-8"))
            start = end
        return out

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.to_list(), dtype=object)

    def to_bytes_array(self) -> np.ndarray:
        """Fixed-width `S(W)` numpy array, built with a vectorized ragged
        gather — no per-element Python.  This is what lets corpus-scale
        (name, term) -> index mapping run at numpy speed (np.unique /
        searchsorted over the S array) instead of a Python loop per feature
        occurrence."""
        n = len(self.offsets)
        if n == 0:
            return np.zeros(0, dtype="S1")
        offs = self.offsets
        lens = np.diff(offs, prepend=0)
        w = max(int(lens.max()), 1)
        buf = np.zeros((n, w), dtype=np.uint8)
        total = int(offs[-1])
        if total:
            starts = offs - lens
            byte_row = np.repeat(np.arange(n), lens)
            byte_pos = np.arange(total) - np.repeat(starts, lens)
            buf[byte_row, byte_pos] = np.frombuffer(self.blob, np.uint8,
                                                    count=total)
        return buf.view(f"S{w}").ravel()

    def to_str_array(self) -> np.ndarray:
        """Unicode array decoded from the fixed-width bytes (vectorized)."""
        return np.char.decode(self.to_bytes_array(), "utf-8")

    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets, prepend=0)

    def take_bytes(self, idx: np.ndarray) -> np.ndarray:
        """Fixed-width `S(W)` array of the SELECTED elements only — the
        padded width is the max over `idx`, not the whole column, so one
        long outlier elsewhere cannot inflate the gather."""
        idx = np.asarray(idx)
        if len(idx) == 0:
            return np.zeros(0, dtype="S1")
        lens_all = self.lengths()
        starts_all = self.offsets - lens_all
        lens = lens_all[idx]
        starts = starts_all[idx]
        w = max(int(lens.max()), 1)
        total = int(lens.sum())
        buf = np.zeros((len(idx), w), dtype=np.uint8)
        if total:
            within = np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)
            src = np.repeat(starts, lens) + within
            blob = np.frombuffer(self.blob, np.uint8)
            buf[np.repeat(np.arange(len(idx)), lens), within] = blob[src]
        return buf.view(f"S{w}").ravel()


def resolve_feature_keys(name_cols: List[StrColumn],
                         term_cols: List[StrColumn],
                         index_map=None, delim: bytes = b"\x01"):
    """(name, term) occurrence stream -> (index_map, col_idx [nnz]).

    The one shared implementation of vectorized feature-key resolution
    (used by both the single-bag reader and the merged GAME reader):
    occurrences are bucketed BY TOTAL KEY LENGTH before the fixed-width
    encode, so memory is bounded by the actual key bytes — one long feature
    name cannot inflate the whole stream's padding.  Python only ever
    touches the per-shard VOCABULARY.

    When `index_map` is None a new map is built (sorted keys + intercept,
    IndexMap.from_keys layout); otherwise unseen keys resolve to -1."""
    from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap

    nlens = np.concatenate([c.lengths() for c in name_cols]) \
        if name_cols else np.zeros(0, np.int64)
    tlens = np.concatenate([c.lengths() for c in term_cols]) \
        if term_cols else np.zeros(0, np.int64)
    total = len(nlens)
    if total == 0:
        imap = index_map if index_map is not None else IndexMap.from_keys([])
        return imap, np.zeros(0, np.int64)
    key_lens = nlens + tlens + len(delim)

    # per-length-bucket fixed-width encode + unique
    names_all = concat_str_columns(name_cols)
    terms_all = concat_str_columns(term_cols)
    bucket_vocabs = []
    bucket_codes = np.zeros(total, np.int64)
    bucket_base: List[int] = []
    order_idx = []
    for L in np.unique(key_lens):
        idx = np.flatnonzero(key_lens == L)
        keys_l = np.char.add(np.char.add(names_all.take_bytes(idx), delim),
                             terms_all.take_bytes(idx))
        uniq_l, codes_l = np.unique(keys_l, return_inverse=True)
        bucket_base.append(sum(len(v) for v in bucket_vocabs))
        bucket_vocabs.append(uniq_l)
        bucket_codes[idx] = codes_l + bucket_base[-1]
        order_idx.append(idx)

    # merge bucket vocabularies into one globally sorted vocabulary
    w = max(int(v.dtype.itemsize) for v in bucket_vocabs)
    cat = np.concatenate([v.astype(f"S{w}") for v in bucket_vocabs])
    uniq, inv = np.unique(cat, return_inverse=True)  # inv: bucket slot -> global
    codes = inv[bucket_codes]

    decoded = [k.decode("utf-8") for k in uniq.tolist()]
    if index_map is None:
        index_map = IndexMap.from_keys(decoded, add_intercept=True)
        if INTERCEPT_KEY in decoded:
            # from_keys moves an explicit intercept key to the LAST slot,
            # breaking the sorted-position identity — fall back to lookup
            lut = np.asarray([index_map.key_to_index[k] for k in decoded],
                             dtype=np.int64)
        else:
            # np.unique sorts S-arrays bytewise; UTF-8 byte order ==
            # code-point order, so positions match from_keys' sorted layout
            lut = np.arange(len(uniq), dtype=np.int64)
    else:
        lut = np.asarray([index_map.key_to_index.get(k, -1)
                          for k in decoded], dtype=np.int64)
    return index_map, lut[codes]


def concat_str_columns(cols: List[StrColumn]) -> StrColumn:
    """Concatenate string columns (offsets of later columns are shifted by
    the cumulative blob length)."""
    if len(cols) == 1:
        return cols[0]
    parts, shift = [], 0
    blobs = []
    for c in cols:
        parts.append(c.offsets + shift)
        blobs.append(c.blob)
        shift += len(c.blob)
    return StrColumn(np.concatenate(parts) if parts else
                     np.zeros(0, np.int64), b"".join(blobs))


@dataclasses.dataclass
class DecodePlan:
    program: np.ndarray             # int32 tokens
    columns: List[Tuple[str, int]]  # (path, KIND_*)


def compile_schema(schema_json, decode_maps: bool = False
                   ) -> Optional[DecodePlan]:
    """Record schema -> op program, or None when a shape is unsupported.
    `decode_maps` materializes map<string,string> fields as key/value/count
    columns (GAME id-tag extraction); off by default — skipping is cheaper."""
    tokens: List[int] = []
    columns: List[Tuple[str, int]] = []
    names: Dict[str, dict] = {}
    in_progress: set = set()

    def new_col(path: str, kind: int) -> int:
        columns.append((path, kind))
        return len(columns) - 1

    def emit(node, path: str) -> bool:
        if isinstance(node, str):
            if node in in_progress:
                return False  # self-referential record: no flat program exists
            if node in names:
                return emit(names[node], path)
            if node == "null":
                return True  # nothing to read, nothing to record
            if node not in _PRIMITIVE_OPS:
                return False
            op, kind = _PRIMITIVE_OPS[node]
            tokens.extend([op, new_col(path, kind)])
            return True
        if isinstance(node, list):  # union: only [null, X] / [X, null]
            if len(node) != 2 or "null" not in node:
                return False
            null_idx = node.index("null")
            other = node[1 - null_idx]
            present = new_col(path + "#present", KIND_I64)
            tokens.extend([OP_OPT, null_idx, present])
            fixup = len(tokens)
            tokens.append(-1)  # body length placeholder
            if not emit(other, path):
                return False
            tokens[fixup] = len(tokens) - fixup - 1
            return True
        t = node["type"]
        if t == "record":
            full = node.get("namespace", "") + "." + node["name"] \
                if node.get("namespace") else node["name"]
            names[full] = names[node["name"]] = node
            in_progress.update((full, node["name"]))
            try:
                for f in node["fields"]:
                    fpath = f"{path}.{f['name']}" if path else f["name"]
                    if not emit(f["type"], fpath):
                        return False
            finally:
                in_progress.difference_update((full, node["name"]))
            return True
        if t == "array":
            count = new_col(path + "#count", KIND_I64)
            tokens.extend([OP_ARRAY, count])
            fixup = len(tokens)
            tokens.append(-1)
            if not emit(node["items"], path):
                return False
            tokens[fixup] = len(tokens) - fixup - 1
            return True
        if t == "map":
            values = node["values"]
            if values not in ("string", "bytes"):
                return False
            if not decode_maps:
                tokens.append(OP_MAP_SKIP)
                return True
            # decoded for GAME ingest: id tags may live in metadataMap
            # (reference: GameConverters.getIdTagToValueMapFromRow falls back
            # to the metadata map when no top-level id column exists); other
            # readers skip maps to keep the hot path free of metadata copies
            count = new_col(path + "#count", KIND_I64)
            kcol = new_col(path + ".key", KIND_STR)
            vcol = new_col(path + ".value", KIND_STR)
            tokens.extend([OP_MAP, count, kcol, vcol])
            return True
        if t == "enum":
            tokens.extend([OP_ENUM, new_col(path, KIND_I64)])
            return True
        if isinstance(t, (dict, list)):
            return emit(t, path)  # {"type": {...nested...}}
        return emit(t, path) if t in names or t in _PRIMITIVE_OPS else False

    if not emit(schema_json, ""):
        return None
    return DecodePlan(np.asarray(tokens, dtype=np.int32), columns)


def read_columnar(path: str, decode_maps: bool = False):
    """Decode a container file into columns, or None when the native path
    is unavailable / the schema is unsupported (callers fall back)."""
    lib = _load_lib()
    if lib is None:
        return None
    schema_json, blocks = iter_raw_blocks(path)
    plan = compile_schema(schema_json, decode_maps=decode_maps)
    if plan is None:
        return None

    ncols = len(plan.columns)
    kinds = np.asarray([k for _, k in plan.columns], dtype=np.int32)
    prog = plan.program
    handle = lib.avrodec_alloc_cols(
        ncols, kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if not handle:
        return None
    try:
        for count, data in blocks:
            consumed = lib.avrodec_decode_block(
                data, len(data), count,
                prog.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                len(prog), handle, ncols)
            if consumed != len(data):
                raise ValueError(
                    f"{path}: native Avro decode failed (consumed {consumed} "
                    f"of {len(data)} block bytes)")
        def as_np(ptr, n, dtype):
            # string_at does one bulk memcpy; frombuffer views it (the
            # ctypeslib.as_array route converts elementwise — far too slow)
            if not n:
                return np.zeros(0, dtype)
            raw = ctypes.string_at(ptr, n * np.dtype(dtype).itemsize)
            return np.frombuffer(raw, dtype=dtype)

        out = {}
        for i, (name, kind) in enumerate(plan.columns):
            n = lib.avrodec_col_len(handle, i)
            if kind == KIND_F64:
                out[name] = as_np(lib.avrodec_col_f64(handle, i), n,
                                  np.float64)
            elif kind == KIND_I64:
                out[name] = as_np(lib.avrodec_col_i64(handle, i), n,
                                  np.int64)
            else:
                bn = lib.avrodec_col_blob_len(handle, i)
                blob = lib.avrodec_col_blob(handle, i)
                out[name] = StrColumn(
                    as_np(lib.avrodec_col_i64(handle, i), n, np.int64),
                    ctypes.string_at(blob, bn) if bn else b"")
        return out
    finally:
        lib.avrodec_free_cols(handle, ncols)
