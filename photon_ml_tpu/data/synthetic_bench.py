"""Statistically-matched synthetic replicas of the benchmark corpora.

The BASELINE configs name two public datasets (a1a, MovieLens-1M/20M) that
cannot be fetched in this environment (zero network egress).  These
generators produce seeded replicas matched to the corpora's published shape
statistics, and every bench result produced from them is labelled
`data: "synthetic-replica"` in the JSON so the numbers are never mistaken
for real-corpus runs.

a1a (LIBSVM adult): n=1605 train rows, d=123 binary one-hot features,
density ~0.115 (a1a stores ~14 active features per row of 123), ~24%
positive labels.  Replicated `replicas`x row-wise for throughput-scale
benchmarks (the reference bench path feeds a1a through
dev-scripts/libsvm_text_to_trainingexample_avro.py + run_photon_ml_driver.sh).

MovieLens-1M: 1,000,209 ratings, 6040 users, 3706 movies, 18 genres;
user activity is heavy-tailed (min 20, median ~96, max 2314 ratings/user).
MovieLens-20M: 20,000,263 ratings, 138,493 users, 26,744 movies, 20 genre
tags (19 + "(no genres listed)").  The GLMix bench task is the KDD'16 paper
setup: binarized response (rating >= 4), fixed effect on global features,
per-user (and per-item) random effects — so the generator plants a true
mixed-effect structure: a global weight vector plus per-user/per-item
weight vectors with controlled variance, guaranteeing random effects carry
real signal (mixed model must beat fixed-only, as in the reference's
DriverTest RMSE orderings).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

# Bump whenever any generator in this module changes its output for a given
# seed.  bench.py folds this into its reference-optimum cache keys so a
# generator change can never silently reuse stale float64 reference NLLs.
GENERATOR_VERSION = "g2"


def make_a1a_features(replicas: int = 1, seed: int = 42,
                      density: float = 0.115) -> np.ndarray:
    """[1605*replicas, 124] binary features (+ intercept column last)."""
    rng = np.random.default_rng(seed)
    n, d = 1605 * replicas, 124
    x = (rng.uniform(size=(n, d)) < density).astype(np.float32)
    x[:, -1] = 1.0
    return x


def make_a1a_like(replicas: int = 1, task: str = "logistic", seed: int = 42):
    """(x, y) at a1a's shape with labels from a planted GLM.

    tasks: logistic (binary 0/1), linear (gaussian), poisson (counts),
    hinge (binary, for the smoothed-hinge SVM config)."""
    x = make_a1a_features(replicas, seed)
    rng = np.random.default_rng(seed + 1)
    n, d = x.shape
    w = (rng.normal(size=d) * 0.7).astype(np.float64)
    z = x.astype(np.float64) @ w
    if task == "logistic" or task == "hinge":
        y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    elif task == "linear":
        y = (z + rng.normal(size=n)).astype(np.float32)
    elif task == "poisson":
        # scale margins down so planted rates stay sane (exp overflow guard)
        y = rng.poisson(np.exp(0.25 * z)).astype(np.float32)
    else:
        raise ValueError(task)
    return x, y


@dataclasses.dataclass
class MovieLensLike:
    """One synthetic-replica ratings table plus its planted truth."""

    user_ids: np.ndarray      # [n] int
    item_ids: np.ndarray      # [n] int
    response: np.ndarray      # [n] float32, binarized rating >= 4
    # feature shards, canonical row order
    x_global: np.ndarray      # [n, d_global] float32 (item genres ++ user
    #                           demographic buckets ++ intercept)
    x_user: np.ndarray        # [n, d_user]  float32 (item genres ++ intercept
    #                           — the per-USER model sees ITEM features)
    x_item: np.ndarray        # [n, d_item]  float32 (user buckets ++ intercept)
    num_users: int
    num_items: int


def make_movielens_like(
    scale: str = "1m",
    seed: int = 7,
    n_rows: Optional[int] = None,
    user_effect_scale: float = 1.0,
    item_effect_scale: float = 0.5,
) -> MovieLensLike:
    """Synthetic replica matched to MovieLens-1M / -20M shape statistics.

    Row counts, user/item cardinalities, and genre dimensionality follow the
    published corpus stats (see module docstring); user activity ~ lognormal
    matched to the heavy tail, item popularity ~ Zipf.  Response is
    logistic( global + per-user + per-item planted effects ).
    """
    if scale == "1m":
        n, num_users, num_items, n_genres = 1_000_209, 6040, 3706, 18
    elif scale == "20m":
        n, num_users, num_items, n_genres = 20_000_263, 138_493, 26_744, 20
    else:
        raise ValueError(scale)
    if n_rows is not None:
        n = int(n_rows)
    rng = np.random.default_rng(seed)

    # --- entities ---------------------------------------------------------
    # user activity: lognormal propensities (heavy tail, every user >= ~20
    # ratings in the real corpus; sampling with replacement approximates it)
    user_prop = rng.lognormal(mean=0.0, sigma=1.1, size=num_users)
    user_prop /= user_prop.sum()
    user_ids = rng.choice(num_users, size=n, p=user_prop).astype(np.int32)
    # item popularity: Zipf-ish via lognormal with a fatter tail
    item_prop = rng.lognormal(mean=0.0, sigma=1.4, size=num_items)
    item_prop /= item_prop.sum()
    item_ids = rng.choice(num_items, size=n, p=item_prop).astype(np.int32)

    # --- static entity features -----------------------------------------
    # items: ~2 genres each on average (multi-hot) + a popularity bucket
    item_genres = (rng.uniform(size=(num_items, n_genres))
                   < (2.0 / n_genres)).astype(np.float32)
    # users: gender (1 col) + 7 age buckets + 4 occupation buckets, one-hot
    n_user_feats = 1 + 7 + 4
    user_feats = np.zeros((num_users, n_user_feats), dtype=np.float32)
    user_feats[:, 0] = rng.uniform(size=num_users) < 0.28  # ML-1M F share
    age = rng.integers(0, 7, size=num_users)
    user_feats[np.arange(num_users), 1 + age] = 1.0
    occ = rng.integers(0, 4, size=num_users)
    user_feats[np.arange(num_users), 8 + occ] = 1.0

    # --- planted truth ----------------------------------------------------
    d_global = n_genres + n_user_feats + 1
    d_user = n_genres + 1          # per-user model over item genres
    d_item = n_user_feats + 1      # per-item model over user buckets
    w_global = rng.normal(size=d_global) * 0.8
    w_user = rng.normal(size=(num_users, d_user)) * user_effect_scale
    w_item = rng.normal(size=(num_items, d_item)) * item_effect_scale

    ig = item_genres[item_ids]                     # [n, n_genres]
    uf = user_feats[user_ids]                      # [n, n_user_feats]
    ones = np.ones((n, 1), dtype=np.float32)
    x_global = np.concatenate([ig, uf, ones], axis=1)
    x_user = np.concatenate([ig, ones], axis=1)
    x_item = np.concatenate([uf, ones], axis=1)

    z = x_global.astype(np.float64) @ w_global
    z = z + np.einsum("nd,nd->n", x_user.astype(np.float64), w_user[user_ids])
    z = z + np.einsum("nd,nd->n", x_item.astype(np.float64), w_item[item_ids])
    response = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

    return MovieLensLike(user_ids=user_ids, item_ids=item_ids,
                         response=response, x_global=x_global,
                         x_user=x_user, x_item=x_item,
                         num_users=num_users, num_items=num_items)


def movielens_shards(ml: MovieLensLike) -> Dict[str, np.ndarray]:
    return {"global": ml.x_global, "per_user": ml.x_user,
            "per_item": ml.x_item}


def make_wide_sparse_logistic(n: int, d: int = 250_000, nnz: int = 64,
                              seed: int = 77):
    """Wide sparse logistic fixture: [n, d] binary CSR with `nnz` active
    features per row (hashed-feature shape; reference: the >200k-feature
    depth-switch regime, GameEstimator.scala:667-669) + labels from a
    planted sparse GLM.  Column d-1 is the intercept."""
    import scipy.sparse as sp
    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(n), nnz)
    cols = rng.integers(0, d - 1, size=n * nnz)
    x = sp.coo_matrix((np.ones(n * nnz, np.float32), (rows, cols)),
                      shape=(n, d)).tocsr()
    x.sum_duplicates()
    x.data[:] = 1.0                      # binary features, exact in bf16
    icpt = sp.csr_matrix(np.ones((n, 1), np.float32))
    x = sp.hstack([x[:, :d - 1], icpt]).tocsr()
    w = (rng.normal(size=d) * (0.35 / np.sqrt(nnz))).astype(np.float64)
    z = x.astype(np.float64) @ w
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    return x, y


@dataclasses.dataclass
class YahooLike:
    """Yahoo!-Music-fixture-shaped GAME data: a WIDE sparse global shard
    (the DriverTest e2e asserts 14,983 fixed-effect coefficients,
    photon-client/src/integTest/.../DriverTest.scala:96-98) + narrow dense
    per-user / per-item shards."""

    user_ids: np.ndarray
    item_ids: np.ndarray
    response: np.ndarray
    x_global: object          # [n, d_global] scipy CSR
    x_user: np.ndarray        # [n, d_user] float32
    x_item: np.ndarray        # [n, d_item] float32
    num_users: int
    num_items: int


def make_yahoo_like(n_rows: int, d_global: int = 14_983, nnz_global: int = 24,
                    num_users: int = 2_000, num_items: int = 10_000,
                    d_user: int = 21, d_item: int = 21,
                    seed: int = 23) -> YahooLike:
    """FE (wide sparse) + per-user RE + per-item RE logistic fixture at the
    Yahoo integration-test shape."""
    import scipy.sparse as sp
    rng = np.random.default_rng(seed)
    n = int(n_rows)
    user_ids = rng.integers(0, num_users, size=n).astype(np.int32)
    item_ids = rng.integers(0, num_items, size=n).astype(np.int32)

    rows = np.repeat(np.arange(n), nnz_global)
    cols = rng.integers(0, d_global - 1, size=n * nnz_global)
    xg = sp.coo_matrix((np.ones(n * nnz_global, np.float32), (rows, cols)),
                       shape=(n, d_global)).tocsr()
    xg.sum_duplicates()
    xg.data[:] = 1.0
    icpt = sp.csr_matrix(np.ones((n, 1), np.float32))
    xg = sp.hstack([xg[:, :d_global - 1], icpt]).tocsr()

    xu = rng.normal(size=(n, d_user)).astype(np.float32)
    xu[:, -1] = 1.0
    xi = rng.normal(size=(n, d_item)).astype(np.float32)
    xi[:, -1] = 1.0

    w_g = (rng.normal(size=d_global) * (0.4 / np.sqrt(nnz_global)))
    w_u = rng.normal(size=(num_users, d_user)) * 0.5
    w_i = rng.normal(size=(num_items, d_item)) * 0.3
    z = xg.astype(np.float64) @ w_g
    z = z + np.einsum("nd,nd->n", xu.astype(np.float64), w_u[user_ids])
    z = z + np.einsum("nd,nd->n", xi.astype(np.float64), w_i[item_ids])
    response = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    return YahooLike(user_ids=user_ids, item_ids=item_ids, response=response,
                     x_global=xg, x_user=xu, x_item=xi,
                     num_users=num_users, num_items=num_items)
