"""Per-feature summary statistics.

reference: BasicStatisticalSummary (photon-lib/.../stat/
BasicStatisticalSummary.scala:36-117), which wraps spark-mllib colStats.
Used to build NormalizationContexts and for the feature-stats output file
(reference: cli/game/training/Driver.calculateAndSaveFeatureShardStats).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np


@dataclasses.dataclass
class BasicStatisticalSummary:
    mean: np.ndarray
    variance: np.ndarray
    count: int
    num_nonzeros: np.ndarray
    max: np.ndarray
    min: np.ndarray
    norm_l1: np.ndarray
    norm_l2: np.ndarray
    mean_abs: np.ndarray

    @property
    def max_magnitude(self) -> np.ndarray:
        return np.maximum(np.abs(self.max), np.abs(self.min))

    @staticmethod
    def from_features(x: np.ndarray, weights: Optional[np.ndarray] = None
                      ) -> "BasicStatisticalSummary":
        x = np.asarray(x)
        n = x.shape[0]
        if weights is None:
            mean = x.mean(axis=0)
            var = x.var(axis=0, ddof=1) if n > 1 else np.zeros(x.shape[1])
        else:
            w = np.asarray(weights)[:, None]
            wsum = w.sum()
            mean = (x * w).sum(axis=0) / wsum
            var = ((x - mean) ** 2 * w).sum(axis=0) / max(wsum - 1.0, 1.0)
        return BasicStatisticalSummary(
            mean=mean, variance=var, count=n,
            num_nonzeros=(x != 0).sum(axis=0),
            max=x.max(axis=0), min=x.min(axis=0),
            norm_l1=np.abs(x).sum(axis=0),
            norm_l2=np.sqrt((x * x).sum(axis=0)),
            mean_abs=np.abs(x).mean(axis=0))

    def to_dict(self) -> Dict[str, list]:
        return {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                for k, v in dataclasses.asdict(self).items()}
