"""Per-feature summary statistics.

reference: BasicStatisticalSummary (photon-lib/.../stat/
BasicStatisticalSummary.scala:36-117), which wraps spark-mllib colStats.
Used to build NormalizationContexts and for the feature-stats output file
(reference: cli/game/training/Driver.calculateAndSaveFeatureShardStats).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np


@dataclasses.dataclass
class BasicStatisticalSummary:
    mean: np.ndarray
    variance: np.ndarray
    count: int
    num_nonzeros: np.ndarray
    max: np.ndarray
    min: np.ndarray
    norm_l1: np.ndarray
    norm_l2: np.ndarray
    mean_abs: np.ndarray

    @property
    def max_magnitude(self) -> np.ndarray:
        return np.maximum(np.abs(self.max), np.abs(self.min))

    @staticmethod
    def from_features(x: np.ndarray, weights: Optional[np.ndarray] = None
                      ) -> "BasicStatisticalSummary":
        x = np.asarray(x)
        n = x.shape[0]
        if weights is None:
            mean = x.mean(axis=0)
            var = x.var(axis=0, ddof=1) if n > 1 else np.zeros(x.shape[1])
        else:
            w = np.asarray(weights)[:, None]
            wsum = w.sum()
            mean = (x * w).sum(axis=0) / wsum
            var = ((x - mean) ** 2 * w).sum(axis=0) / max(wsum - 1.0, 1.0)
        return BasicStatisticalSummary(
            mean=mean, variance=var, count=n,
            num_nonzeros=(x != 0).sum(axis=0),
            max=x.max(axis=0), min=x.min(axis=0),
            norm_l1=np.abs(x).sum(axis=0),
            norm_l2=np.sqrt((x * x).sum(axis=0)),
            mean_abs=np.abs(x).mean(axis=0))

    @staticmethod
    def from_sparse(x, weights: Optional[np.ndarray] = None
                    ) -> "BasicStatisticalSummary":
        """CSR/CSC shard summary without densifying (the wide regime);
        weighted mean/variance match from_features' semantics exactly."""
        import scipy.sparse as sp
        csr = x.tocsr()
        n, d = csr.shape
        sq = csr.multiply(csr)
        if weights is None:
            mean = np.asarray(csr.mean(axis=0)).ravel()
            ex2 = np.asarray(sq.mean(axis=0)).ravel()
            var = (ex2 * n - n * mean ** 2) / max(n - 1, 1)
        else:
            w = np.asarray(weights, np.float64)
            wsum = float(w.sum())
            mean = np.asarray(w @ csr).ravel() / wsum
            # sum_i w_i (x_i - mean)^2 = sum w x^2 - 2 mean sum w x + mean^2 sum w
            wx2 = np.asarray(w @ sq).ravel()
            var = (wx2 - wsum * mean ** 2) / max(wsum - 1.0, 1.0)
        nnz = np.asarray((csr != 0).sum(axis=0)).ravel()
        mx = np.asarray(csr.max(axis=0).todense()).ravel()
        mn = np.asarray(csr.min(axis=0).todense()).ravel()
        absx = sp.csr_matrix((np.abs(csr.data), csr.indices, csr.indptr),
                             shape=csr.shape)
        l1 = np.asarray(absx.sum(axis=0)).ravel()
        return BasicStatisticalSummary(
            mean=mean, variance=np.maximum(var, 0.0), count=n,
            num_nonzeros=nnz, max=mx, min=mn, norm_l1=l1,
            norm_l2=np.sqrt(np.asarray(sq.sum(axis=0)).ravel()),
            mean_abs=l1 / max(n, 1))

    def to_dict(self) -> Dict[str, list]:
        return {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                for k, v in dataclasses.asdict(self).items()}
