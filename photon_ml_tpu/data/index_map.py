"""Feature index maps: (name, term) <-> dense column index.

Rebuild of the reference's feature-identity machinery:
  - NameAndTerm / feature-key building (photon-client/.../data/avro/NameAndTerm.scala,
    util/Utils.getFeatureKey — key = name + DELIMITER + term)
  - IndexMap / DefaultIndexMap / DefaultIndexMapLoader
    (photon-api/.../util/{IndexMap,DefaultIndexMap,DefaultIndexMapLoader}.scala)
  - PalDBIndexMap + FeatureIndexingJob (photon-api/.../util/PalDBIndexMap.scala:43-278,
    photon-client/.../FeatureIndexingJob.scala:56-307)

The PalDB off-heap store existed because JVM heaps choke on 1e8-entry hash
maps; here a plain columnar file (npz of two string arrays + json metadata)
holds the same map compactly, memory-maps instantly, and needs no partition
offset arithmetic.  The INTERCEPT pseudo-feature matches the reference's
Constants.INTERCEPT_KEY convention: always present, always the LAST index
(so factor/shift pinning and warm starts stay aligned).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterable, Optional, Sequence

import numpy as np

DELIMITER = "\x01"       # reference: Constants name.term delimiter
INTERCEPT_NAME = "(INTERCEPT)"  # reference: Constants intercept key
INTERCEPT_KEY = INTERCEPT_NAME + DELIMITER


def feature_key(name: str, term: str = "") -> str:
    """reference: Utils.getFeatureKey — identity is the (name, term) pair."""
    return f"{name}{DELIMITER}{term}"


@dataclasses.dataclass
class IndexMap:
    """Immutable bidirectional feature map for one feature shard."""

    key_to_index: Dict[str, int]
    index_to_key: np.ndarray  # [d] object array of keys

    @property
    def size(self) -> int:
        return len(self.index_to_key)

    @property
    def has_intercept(self) -> bool:
        return INTERCEPT_KEY in self.key_to_index

    @property
    def intercept_index(self) -> Optional[int]:
        return self.key_to_index.get(INTERCEPT_KEY)

    def index_of(self, name: str, term: str = "") -> int:
        """-1 for unseen features (reference IndexMap.getIndex miss -> -1)."""
        return self.key_to_index.get(feature_key(name, term), -1)

    def key_of(self, index: int) -> str:
        return str(self.index_to_key[index])

    def name_term(self, index: int) -> tuple[str, str]:
        name, _, term = self.key_of(index).partition(DELIMITER)
        return name, term

    # -- persistence (replaces PalDB store files) -----------------------------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez_compressed(path if path.endswith(".npz") else path + ".npz",
                            keys=self.index_to_key.astype(object))

    @staticmethod
    def load(path: str) -> "IndexMap":
        data = np.load(path if path.endswith(".npz") else path + ".npz",
                       allow_pickle=True)
        keys = data["keys"]
        return IndexMap({str(k): i for i, k in enumerate(keys)}, keys)

    @staticmethod
    def from_keys(keys: Sequence[str], add_intercept: bool = True) -> "IndexMap":
        """Deterministic map: sorted unique keys, intercept last.

        reference: FeatureIndexingJob builds per-partition sorted distinct
        feature names; sorting here gives run-to-run determinism without the
        hash-partition offset bookkeeping."""
        uniq = sorted(set(keys) - {INTERCEPT_KEY})
        if add_intercept:
            uniq.append(INTERCEPT_KEY)
        arr = np.asarray(uniq, dtype=object)
        return IndexMap({k: i for i, k in enumerate(uniq)}, arr)


def build_index_map(
    feature_names: Iterable[tuple[str, str]], add_intercept: bool = True,
) -> IndexMap:
    """FeatureIndexingJob equivalent: scan (name, term) pairs -> IndexMap.
    reference: FeatureIndexingJob.partitionedUniqueFeatures (line 92-138)."""
    return IndexMap.from_keys([feature_key(n, t) for n, t in feature_names],
                              add_intercept=add_intercept)


@dataclasses.dataclass
class IndexMapCollection:
    """Per-feature-shard maps + metadata file (replaces the per-shard PalDB
    namespace dirs of FeatureIndexingJob)."""

    shards: Dict[str, IndexMap]

    def save(self, directory: str) -> None:
        from photon_ml_tpu.utils.durable import atomic_write_json
        os.makedirs(directory, exist_ok=True)
        meta = {"shards": sorted(self.shards)}
        atomic_write_json(os.path.join(directory, "index-maps.json"), meta)
        for shard, imap in self.shards.items():
            imap.save(os.path.join(directory, f"{shard}.index.npz"))

    @staticmethod
    def load(directory: str) -> "IndexMapCollection":
        with open(os.path.join(directory, "index-maps.json")) as f:
            meta = json.load(f)
        return IndexMapCollection({
            shard: IndexMap.load(os.path.join(directory, f"{shard}.index.npz"))
            for shard in meta["shards"]})
