"""Minimal pure-Python Avro Object Container File codec.

The environment has no avro/fastavro package, and the reference's entire I/O
surface is Avro (photon-avro-schemas/src/main/avro/*.avsc; readers/writers in
photon-client/.../data/avro/AvroUtils.scala).  This module implements the
published Avro 1.x specification subset those schemas need:

  types:  null, boolean, int, long, float, double, bytes, string,
          record, enum, array, map, union, fixed
  files:  Object Container Format (magic Obj\\x01, metadata map with
          avro.schema/avro.codec, 16-byte sync marker, data blocks)
  codecs: null, deflate (raw zlib)

Generic data model: records are dicts, arrays are lists, unions pick the
first matching branch.  This is an independent implementation from the Avro
spec, not a port of any Avro library.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator, List, Optional

MAGIC = b"Obj\x01"
DEFAULT_SYNC = b"\x50\x48\x4f\x54\x4f\x4e\x2d\x54\x50\x55\x2d\x53\x59\x4e\x43\x21"  # 16B

# ---------------------------------------------------------------------------
# primitive binary encoding
# ---------------------------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n >= 0 else (((-n) << 1) - 1)


def write_long(buf: io.BytesIO, n: int) -> None:
    z = (n << 1) ^ (n >> 63)  # arithmetic shift handles negatives
    z &= (1 << 64) - 1
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            break


def read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("unexpected end of Avro data")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag decode


def write_bytes(buf: io.BytesIO, b: bytes) -> None:
    write_long(buf, len(b))
    buf.write(b)


def read_bytes(buf: BinaryIO) -> bytes:
    n = read_long(buf)
    return buf.read(n)


# ---------------------------------------------------------------------------
# schema-driven encode/decode
# ---------------------------------------------------------------------------


class Schema:
    """Parsed schema with named-type registry (records referenced by name)."""

    def __init__(self, schema_json: Any):
        self.names: dict[str, Any] = {}
        self.root = self._resolve(schema_json)

    def _resolve(self, s: Any) -> Any:
        if isinstance(s, str):
            if s in ("null", "boolean", "int", "long", "float", "double",
                     "bytes", "string"):
                return s
            if s in self.names:
                return self.names[s]
            raise ValueError(f"unknown type name {s!r}")
        if isinstance(s, list):
            return ["union", [self._resolve(b) for b in s]]
        t = s["type"]
        if t in ("record", "error"):
            rec = {"type": "record", "name": s["name"], "fields": []}
            self.names[s["name"]] = rec
            full = s.get("namespace", "") + "." + s["name"] if s.get("namespace") else s["name"]
            self.names[full] = rec
            rec["fields"] = [{"name": f["name"],
                              "type": self._resolve(f["type"]),
                              "default": f.get("default")}
                             for f in s["fields"]]
            return rec
        if t == "enum":
            e = {"type": "enum", "name": s["name"], "symbols": s["symbols"]}
            self.names[s["name"]] = e
            return e
        if t == "fixed":
            fx = {"type": "fixed", "name": s["name"], "size": s["size"]}
            self.names[s["name"]] = fx
            return fx
        if t == "array":
            return {"type": "array", "items": self._resolve(s["items"])}
        if t == "map":
            return {"type": "map", "values": self._resolve(s["values"])}
        return self._resolve(t)  # {"type": "string"} style


def _branch_matches(branch: Any, value: Any) -> bool:
    kind = branch if isinstance(branch, str) else branch.get("type", "union")
    if kind == "null":
        return value is None
    if value is None:
        return False
    if kind == "boolean":
        return isinstance(value, bool)
    if kind in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if kind in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if kind == "string":
        return isinstance(value, str)
    if kind in ("bytes", "fixed"):
        return isinstance(value, bytes)
    if kind == "record":
        return isinstance(value, dict)
    if kind == "map":
        return isinstance(value, dict)
    if kind == "array":
        return isinstance(value, (list, tuple))
    if kind == "enum":
        return isinstance(value, str)
    return False


def encode(buf: io.BytesIO, schema: Any, value: Any) -> None:
    kind = schema if isinstance(schema, str) else (
        "union" if isinstance(schema, list) and schema[0] == "union" else schema["type"])
    if kind == "null":
        return
    if kind == "boolean":
        buf.write(b"\x01" if value else b"\x00")
    elif kind in ("int", "long"):
        write_long(buf, int(value))
    elif kind == "float":
        buf.write(struct.pack("<f", float(value)))
    elif kind == "double":
        buf.write(struct.pack("<d", float(value)))
    elif kind == "bytes":
        write_bytes(buf, value)
    elif kind == "string":
        write_bytes(buf, value.encode("utf-8"))
    elif kind == "fixed":
        assert len(value) == schema["size"]
        buf.write(value)
    elif kind == "enum":
        write_long(buf, schema["symbols"].index(value))
    elif kind == "union":
        branches = schema[1]
        for i, branch in enumerate(branches):
            if _branch_matches(branch, value):
                write_long(buf, i)
                encode(buf, branch, value)
                return
        raise TypeError(f"value {value!r} matches no union branch")
    elif kind == "array":
        if value:
            write_long(buf, len(value))
            for item in value:
                encode(buf, schema["items"], item)
        write_long(buf, 0)
    elif kind == "map":
        if value:
            write_long(buf, len(value))
            for k, v in value.items():
                write_bytes(buf, k.encode("utf-8"))
                encode(buf, schema["values"], v)
        write_long(buf, 0)
    elif kind == "record":
        for f in schema["fields"]:
            fv = value.get(f["name"], f.get("default"))
            encode(buf, f["type"], fv)
    else:
        raise ValueError(f"unsupported schema kind {kind!r}")


def decode(buf: BinaryIO, schema: Any) -> Any:
    kind = schema if isinstance(schema, str) else (
        "union" if isinstance(schema, list) and schema[0] == "union" else schema["type"])
    if kind == "null":
        return None
    if kind == "boolean":
        return buf.read(1) == b"\x01"
    if kind in ("int", "long"):
        return read_long(buf)
    if kind == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if kind == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if kind == "bytes":
        return read_bytes(buf)
    if kind == "string":
        return read_bytes(buf).decode("utf-8")
    if kind == "fixed":
        return buf.read(schema["size"])
    if kind == "enum":
        return schema["symbols"][read_long(buf)]
    if kind == "union":
        return decode(buf, schema[1][read_long(buf)])
    if kind == "array":
        out: List[Any] = []
        while True:
            n = read_long(buf)
            if n == 0:
                break
            if n < 0:  # block with byte size prefix
                read_long(buf)
                n = -n
            for _ in range(n):
                out.append(decode(buf, schema["items"]))
        return out
    if kind == "map":
        res = {}
        while True:
            n = read_long(buf)
            if n == 0:
                break
            if n < 0:
                read_long(buf)
                n = -n
            for _ in range(n):
                k = read_bytes(buf).decode("utf-8")
                res[k] = decode(buf, schema["values"])
        return res
    if kind == "record":
        return {f["name"]: decode(buf, f["type"]) for f in schema["fields"]}
    raise ValueError(f"unsupported schema kind {kind!r}")


# ---------------------------------------------------------------------------
# Object Container Files
# ---------------------------------------------------------------------------


def write_container(path: str, schema_json: Any, records: Iterable[dict],
                    codec: str = "deflate", block_records: int = 4096) -> None:
    schema = Schema(schema_json)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = io.BytesIO()
        header = {"avro.schema": json.dumps(schema_json).encode(),
                  "avro.codec": codec.encode()}
        write_long(meta, len(header))
        for k, v in header.items():
            write_bytes(meta, k.encode())
            write_bytes(meta, v)
        write_long(meta, 0)
        f.write(meta.getvalue())
        f.write(DEFAULT_SYNC)

        batch: List[dict] = []

        def flush():
            if not batch:
                return
            body = io.BytesIO()
            for r in batch:
                encode(body, schema.root, r)
            data = body.getvalue()
            if codec == "deflate":
                # raw deflate (no zlib header/checksum), per the Avro spec
                co = zlib.compressobj(9, zlib.DEFLATED, -15)
                data = co.compress(data) + co.flush()
            elif codec != "null":
                raise ValueError(f"unsupported codec {codec}")
            blk = io.BytesIO()
            write_long(blk, len(batch))
            write_long(blk, len(data))
            f.write(blk.getvalue())
            f.write(data)
            f.write(DEFAULT_SYNC)
            batch.clear()

        for rec in records:
            batch.append(rec)
            if len(batch) >= block_records:
                flush()
        flush()


def _read_header(f: BinaryIO, path: str):
    """-> (schema_json, codec, sync marker); leaves f at the first block."""
    if f.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    header = {}
    while True:
        n = read_long(f)
        if n == 0:
            break
        if n < 0:
            read_long(f)
            n = -n
        for _ in range(n):
            k = read_bytes(f).decode()
            header[k] = read_bytes(f)
    schema_json = json.loads(header["avro.schema"])
    codec = header.get("avro.codec", b"null").decode()
    return schema_json, codec, f.read(16)


def iter_raw_blocks(path: str):
    """-> (schema_json, iterator of (record_count, decompressed bytes)).

    The block-granular read path for vectorized/native decoders.  The header
    is read eagerly and the file closed; the generator reopens it, so an
    abandoned iterator never holds an fd."""
    with open(path, "rb") as f:
        schema_json, codec, _sync = _read_header(f, path)
        data_start = f.tell()

    def blocks():
        with open(path, "rb") as f:
            f.seek(data_start)
            while True:
                try:
                    count = read_long(f)
                except EOFError:
                    return
                size = read_long(f)
                data = f.read(size)
                if codec == "deflate":
                    data = zlib.decompress(data, -15)
                elif codec != "null":
                    raise ValueError(f"unsupported codec {codec}")
                f.read(16)  # sync marker
                yield count, data

    return schema_json, blocks()


def read_container(path: str) -> Iterator[dict]:
    with open(path, "rb") as f:
        schema_json, codec, sync = _read_header(f, path)
        schema = Schema(schema_json)
        while True:
            try:
                count = read_long(f)
            except EOFError:
                return
            size = read_long(f)
            data = f.read(size)
            if codec == "deflate":
                data = zlib.decompress(data, -15)
            elif codec != "null":
                raise ValueError(f"unsupported codec {codec}")
            body = io.BytesIO(data)
            for _ in range(count):
                yield decode(body, schema.root)
            if f.read(16) != sync:
                raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
