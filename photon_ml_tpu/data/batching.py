"""Dataset -> device-block builders: the shuffle work, done once at prep time.

Rebuild of the reference's per-coordinate dataset machinery:
  - FixedEffectDataSet (photon-api/.../data/FixedEffectDataSet.scala:30-148)
  - RandomEffectDataSet build: group-by-entity, per-entity sample cap with
    weight rescaling, passive data, feature selection
    (photon-api/.../data/RandomEffectDataSet.scala:240-472)
  - LocalDataSet feature filtering (Pearson), local sampling
    (photon-api/.../data/LocalDataSet.scala:36-321)
  - IndexMapProjector: per-entity dense local feature space
    (photon-api/.../projector/IndexMapProjectorRDD.scala:32-208)
  - RandomEffectDataConfiguration / FixedEffectDataConfiguration
    (photon-api/.../data/{RandomEffect,FixedEffect}DataConfiguration.scala)

Where the reference shuffles (groupByKey by REId, MinHeap combineByKey for
the reservoir cap) every time a dataset is built on the cluster, here the
grouping/capping/projection run once on host numpy and emit static device
blocks; the training loop touches only dense arrays after this point.
"""
from __future__ import annotations

import dataclasses
import functools
import weakref
from typing import Dict, List, Optional, Tuple  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.parallel.random_effect import EntityBlocks
from photon_ml_tpu.utils.math import ceil_pow2 as _ceil_pow2

_SAFE_LABEL = 0.5  # valid for every loss family; see pad_batch_to_mesh


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfig:
    """reference: FixedEffectDataConfiguration.scala (featureShardId; the
    minNumPartitions knob is meaningless here — sharding is the mesh's)."""

    feature_shard: str


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfig:
    """reference: RandomEffectDataConfiguration.scala:42-140.
    `active_data_upper_bound` caps per-entity samples (reservoir-style, with
    weight rescaling); rows beyond the cap become passive data (scored, not
    trained on) when the entity has more than `passive_data_lower_bound`
    rows.  `features_to_samples_ratio` triggers per-entity Pearson feature
    selection.  `projector` in {"index_map", "identity"}."""

    random_effect_type: str
    feature_shard: str
    active_data_upper_bound: Optional[int] = None
    passive_data_lower_bound: Optional[int] = None
    features_to_samples_ratio: Optional[float] = None
    # "index_map" | "identity" | "random_projection:<k>"
    # (reference: ProjectorType.scala — IndexMapProjection, IdentityProjection,
    # RandomProjection(dim))
    projector: str = "index_map"
    seed: int = 7
    # cap on the number of S-buckets: each bucket shape is a separate XLA
    # compile of the vmapped per-entity solver, so unbounded power-of-two
    # classes trade compile wall-clock for padding efficiency.  None = one
    # bucket per power-of-two class.
    max_buckets: Optional[int] = 4
    # keep the host numpy block arrays alongside the device copies so the
    # coordinate residency manager can EVICT the device blocks between
    # coordinate-descent visits and re-stream them from host (out-of-core
    # mode).  Costs one extra host copy of the blocks; off by default — the
    # resident path then transfers eagerly and frees the host staging
    # arrays exactly as before.
    keep_host_blocks: bool = False


@dataclasses.dataclass
class FixedEffectDataset:
    """Flat [n] arrays for one shard, canonical row order."""

    x: np.ndarray
    labels: np.ndarray
    weights: Optional[np.ndarray]
    offsets: Optional[np.ndarray]
    feature_shard: str

    @staticmethod
    def build(dataset: GameDataset, config: FixedEffectDataConfig) -> "FixedEffectDataset":
        return FixedEffectDataset(
            x=dataset.feature_shards[config.feature_shard],
            labels=dataset.response,
            weights=dataset.weights,
            offsets=dataset.offsets,
            feature_shard=config.feature_shard)


@functools.partial(jax.jit, static_argnames=("dtype",))
def _gather_flat_offsets(flat, safe_ids, mask, dtype):
    """Canonical-order offsets -> [Eb, Sb] block layout, one fused program
    (addScoresToOffsets runs per bucket per coordinate update; op-by-op it
    costs several executable uploads per shape on a tunneled device)."""
    return (flat[safe_ids] * mask).astype(dtype)


@dataclasses.dataclass
class EntityBucket:
    """One size-class of entities: lanes [lane_start, lane_start + Eb) of the
    dataset's count-descending lane order, padded to this bucket's own S.

    SURVEY §7 "Hard parts" — bucketed batches: one hot entity must not pad
    every block, so entities are grouped by ceil-power-of-two sample count
    and each class is padded only to its own max (the reference never faces
    this because its per-entity data is ragged RDD rows).

    Device residency: `blocks` is a lazily materialized device copy.  In the
    default (resident) build the device copy is created eagerly at build
    time and `host_blocks` is None — steady state identical to the
    pre-out-of-core code.  With keep_host_blocks the numpy originals stay in
    `host_blocks`, `evict()` drops the device copy between coordinate-
    descent visits, and the next `blocks` access re-streams it — the
    re-stream source of the HBM residency budget (game/residency.py)."""

    lane_start: int
    row_ids: np.ndarray             # [Eb, Sb] canonical row ids, -1 = pad
    host_blocks: Optional[EntityBlocks] = None    # numpy leaves (re-stream src)
    _blocks: Optional[EntityBlocks] = dataclasses.field(default=None,
                                                        repr=False,
                                                        compare=False)
    _safe_ids_dev: object = dataclasses.field(default=None, repr=False,
                                              compare=False)

    @property
    def num_entities(self) -> int:
        return self.row_ids.shape[0]

    @property
    def samples_per_entity(self) -> int:
        return self.row_ids.shape[1]

    @property
    def dim(self) -> int:
        src = self._blocks if self._blocks is not None else self.host_blocks
        return src.x.shape[2]

    @property
    def block_dtype(self):
        """Dtype the DEVICE blocks carry (host staging arrays may be wider:
        float64 host -> float32 device under the default jax config)."""
        if self._blocks is not None:
            return self._blocks.x.dtype
        return jnp.dtype(jax.dtypes.canonicalize_dtype(
            self.host_blocks.x.dtype))

    @property
    def blocks(self) -> EntityBlocks:
        """Device EntityBlocks, transferred on first access (or re-streamed
        after an evict())."""
        if self._blocks is None:
            h = self.host_blocks
            if h is None:
                raise ValueError("bucket was built without host blocks and "
                                 "its device copy is gone; rebuild the "
                                 "random-effect dataset")
            self._blocks = EntityBlocks(
                x=jnp.asarray(h.x), labels=jnp.asarray(h.labels),
                mask=jnp.asarray(h.mask),
                weights=None if h.weights is None else jnp.asarray(h.weights),
                offsets=None if h.offsets is None else jnp.asarray(h.offsets))
        return self._blocks

    @property
    def is_resident(self) -> bool:
        return self._blocks is not None

    def evict(self) -> None:
        """Drop the device copy (requires host_blocks to re-stream)."""
        if self.host_blocks is None:
            return  # nothing to re-stream from: keep the device copy
        self._blocks = None
        self._safe_ids_dev = None

    def device_bytes(self) -> int:
        """Bytes this bucket holds (or would hold) on device."""
        src = self._blocks if self._blocks is not None else self.host_blocks
        if src is None:
            return 0
        total = 0
        for leaf in (src.x, src.labels, src.mask, src.weights, src.offsets):
            if leaf is None:
                continue
            itemsize = np.dtype(
                jax.dtypes.canonicalize_dtype(leaf.dtype)).itemsize
            total += int(np.prod(leaf.shape)) * itemsize
        return total

    def safe_ids_dev(self) -> jnp.ndarray:
        """Device copy of clamped row ids, transferred once per bucket."""
        if self._safe_ids_dev is None:
            self._safe_ids_dev = jnp.asarray(
                np.maximum(self.row_ids, 0).astype(np.int32))
        return self._safe_ids_dev

    def with_offsets_from_flat(self, flat_offsets) -> EntityBlocks:
        blocks = self.blocks
        off = _gather_flat_offsets(jnp.asarray(flat_offsets),
                                   self.safe_ids_dev(), blocks.mask,
                                   jnp.dtype(blocks.x.dtype).name)
        return blocks.with_offsets(off)


@dataclasses.dataclass
class RandomEffectDataset:
    """Per-entity training blocks + the index plumbing to score flat rows.

    reference: RandomEffectDataSet (activeData + uniqueId->REId map +
    passiveData) — here the "joins" are materialized index arrays:
      - entity_position[v]: vocab entity v -> block lane (-1 if unseen)
      - active_row_ids[e, s]: block cell -> canonical row id (-1 pad), which
        also realizes addScoresToOffsets as one gather

    Entities live in count-descending lane order, partitioned into S-buckets
    (`buckets`); `blocks` / `active_row_ids` are single-S compatibility views
    padded to the global max (materialized lazily — the plain random-effect
    solve path iterates buckets and never builds them).
    """

    config: RandomEffectDataConfig
    buckets: list  # List[EntityBucket], contiguous lanes, ascending start
    entity_ids: np.ndarray          # [E] vocab indices, block lane order
    entity_position: np.ndarray     # [V] vocab index -> block lane or -1
    projection: Optional[np.ndarray]  # [E, d_local] global col ids, -1 pad
    global_dim: int
    num_active: int
    num_passive: int
    # dense Gaussian random-projection matrix [d_local, d_global], shared by
    # all entities (reference: ProjectionMatrixBroadcast) — exclusive with
    # the per-entity index `projection`
    projection_matrix: Optional[np.ndarray] = None
    # canonical rows capped out of entities whose LEFTOVER count is at/below
    # passive_data_lower_bound: DISCARDED, not scored (reference:
    # RandomEffectDataSet.scala:399-446 keeps passive data only for entities
    # whose passive count exceeds the bound) — flat_entity_lanes maps them to
    # lane -1 so they contribute score 0, the missing-score default.
    discarded_rows: Optional[np.ndarray] = None  # [k] canonical row ids
    _global_blocks: Optional[EntityBlocks] = dataclasses.field(
        default=None, repr=False, compare=False)
    _global_row_ids: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)

    @property
    def local_dim(self) -> int:
        return self.buckets[0].dim

    @property
    def dtype(self):
        return self.buckets[0].block_dtype

    @property
    def max_samples(self) -> int:
        return max(b.samples_per_entity for b in self.buckets)

    def padding_stats(self) -> Dict[str, float]:
        """Fraction of block cells holding real rows, bucketed vs the
        single-S layout it replaces (VERDICT r2 item #2's efficiency stat)."""
        cells = sum(b.num_entities * b.samples_per_entity
                    for b in self.buckets)
        single = self.num_entities * self.max_samples
        return {"num_buckets": len(self.buckets),
                "bucketed_efficiency": self.num_active / max(cells, 1),
                "single_block_efficiency": self.num_active / max(single, 1)}

    @property
    def active_row_ids(self) -> np.ndarray:
        """[E, S_max] single-S view (lazily materialized)."""
        if self._global_row_ids is None:
            S = self.max_samples
            parts = [np.pad(b.row_ids, ((0, 0), (0, S - b.row_ids.shape[1])),
                            constant_values=-1) for b in self.buckets]
            self._global_row_ids = np.concatenate(parts, axis=0)
        return self._global_row_ids

    @property
    def blocks(self) -> EntityBlocks:
        """Single-S EntityBlocks view over all lanes (lazily materialized;
        the factored-RE latent refit consumes one flat block set)."""
        if self._global_blocks is None:
            S = self.max_samples
            def cat(get, fill):
                if any(get(b.blocks) is None for b in self.buckets):
                    return None
                return jnp.concatenate([
                    jnp.pad(get(b.blocks),
                            ((0, 0), (0, S - b.blocks.samples_per_entity))
                            + ((0, 0),) * (get(b.blocks).ndim - 2),
                            constant_values=fill)
                    for b in self.buckets], axis=0)
            self._global_blocks = EntityBlocks(
                x=cat(lambda b: b.x, 0.0), labels=cat(lambda b: b.labels, _SAFE_LABEL),
                mask=cat(lambda b: b.mask, 0.0), weights=cat(lambda b: b.weights, 0.0),
                offsets=cat(lambda b: b.offsets, 0.0))
        return self._global_blocks

    _safe_ids_dev: object = dataclasses.field(default=None, repr=False,
                                              compare=False)

    def with_offsets_from_flat(self, flat_offsets) -> EntityBlocks:
        """addScoresToOffsets (reference: RandomEffectDataSet.scala:68-88):
        gather the canonical-order offset vector into block layout
        (single-S view; bucketed consumers use EntityBucket's)."""
        blocks = self.blocks
        if self._safe_ids_dev is None:
            self._safe_ids_dev = jnp.asarray(
                np.maximum(self.active_row_ids, 0).astype(np.int32))
        off = _gather_flat_offsets(jnp.asarray(flat_offsets),
                                   self._safe_ids_dev, blocks.mask,
                                   jnp.dtype(blocks.x.dtype).name)
        return blocks.with_offsets(off)

    def scatter_to_global(self, local_coefficients) -> jnp.ndarray:
        """[E, d_local] local-space coefficients -> [E, d_global]
        (reference: IndexMapProjector.projectCoefficients /
        ProjectionMatrix.projectCoefficients = P^T c)."""
        if self.projection_matrix is not None:
            return jnp.asarray(local_coefficients) @ jnp.asarray(self.projection_matrix)
        from photon_ml_tpu.parallel.random_effect import scatter_local_to_global
        return scatter_local_to_global(jnp.asarray(local_coefficients),
                                       self.projection, self.global_dim)

    def evict_device_blocks(self) -> None:
        """Drop every device block copy (buckets + the single-S views).
        Requires keep_host_blocks on the build config; buckets without a
        host source keep their device copy (evict is then a no-op for
        them).  Next access re-streams lazily — the residency manager's
        between-visits rotation (game/residency.py)."""
        for b in self.buckets:
            b.evict()
        self._global_blocks = None       # (_global_row_ids is host: kept)
        self._safe_ids_dev = None

    def device_bytes(self) -> int:
        """Device bytes of all bucket blocks (+ the single-S view when it
        has been materialized — the factored-RE path holds both)."""
        total = sum(b.device_bytes() for b in self.buckets)
        g = self._global_blocks
        if g is not None:
            total += sum(int(leaf.nbytes) for leaf in
                         (g.x, g.labels, g.mask, g.weights, g.offsets)
                         if leaf is not None)
        return total

    def flat_entity_lanes(self, entity_index: np.ndarray) -> np.ndarray:
        """Map a canonical-order entity-index column to block lanes.
        Discarded rows (capped out of below-bound entities) get lane -1."""
        idx = np.asarray(entity_index)
        lanes = np.full_like(idx, -1)
        valid = idx >= 0
        lanes[valid] = self.entity_position[idx[valid]]
        if self.discarded_rows is not None and len(self.discarded_rows):
            lanes[self.discarded_rows] = -1
        return lanes


# (dataset -> {(config, dtype) -> built blocks}) memo: grid sweeps and
# hyperparameter tuning refit the same data under many lambdas — the blocks
# depend only on (data, config, seed), never on the lambdas being searched
_BUILD_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def build_random_effect_dataset(
    dataset: GameDataset,
    config: RandomEffectDataConfig,
    dtype=np.float64,
) -> RandomEffectDataset:
    """Group-by-entity -> cap -> select features -> project -> pad.
    Memoized per (dataset, config, dtype) — see _BUILD_CACHE.

    reference call path: RandomEffectDataSet.apply (scala:240-277) +
    featureSelectionOnActiveData (scala:457-471) +
    RandomEffectDataSetInProjectedSpace.buildWithProjectorType."""
    per_ds = _BUILD_CACHE.setdefault(dataset, {})
    key = (config, np.dtype(dtype).name)
    if key in per_ds:
        return per_ds[key]
    built = _build_random_effect_dataset(dataset, config, dtype)
    per_ds[key] = built
    return built


def _is_np_dense(x) -> bool:
    try:
        import scipy.sparse as sp
        return not sp.issparse(x)
    except ImportError:
        return True


def _build_random_effect_dataset(
    dataset: GameDataset,
    config: RandomEffectDataConfig,
    dtype,
) -> RandomEffectDataset:
    """Fully vectorized build: one lexsort replaces groupByKey, the per-entity
    reservoir cap is a segmented random-key rank cut, the index-map projector
    is segment reductions over the group-sorted rows, and entities are packed
    into power-of-two S-buckets in count-descending lane order.  No O(E)
    Python loops anywhere (VERDICT r2 item #2; reference:
    RandomEffectDataSet.scala:240-472 + MinHeapWithFixedCapacity)."""
    re_type = config.random_effect_type
    x_flat = np.asarray(dataset.feature_shards[config.feature_shard], dtype=dtype)
    y_flat = np.asarray(dataset.response, dtype=dtype)
    w_flat = None if dataset.weights is None else np.asarray(dataset.weights, dtype)
    o_flat = None if dataset.offsets is None else np.asarray(dataset.offsets, dtype)
    ent = np.asarray(dataset.entity_indices[re_type])
    n, d_global = x_flat.shape
    rng = np.random.default_rng(config.seed)

    present = ent >= 0
    uniq = np.unique(ent[present])
    E = len(uniq)
    if E == 0:
        raise ValueError(f"no rows carry entity ids for {re_type!r}")

    # group rows per entity (one argsort — the groupByKey replacement);
    # within an entity, canonical row order is preserved (stable sort)
    uniq_rank_of = np.full(dataset.num_entities(re_type), -1, dtype=np.int64)
    uniq_rank_of[uniq] = np.arange(E)
    grp_all = uniq_rank_of[ent[present]]
    order = np.argsort(grp_all, kind="stable")
    rows_sorted = np.flatnonzero(present)[order]     # canonical ids, grouped
    grp = grp_all[order]                             # uniq-rank per sorted row
    counts = np.bincount(grp, minlength=E)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])

    # --- reservoir cap: segmented random-key rank cut --------------------
    cap = config.active_data_upper_bound
    weight_scale = np.ones(E)
    num_passive = 0
    discarded_rows = np.zeros((0,), dtype=np.int64)
    if cap is not None and (counts > cap).any():
        keys = rng.random(len(rows_sorted))
        rand_order = np.lexsort((keys, grp))
        rank_in_entity = np.arange(len(rows_sorted)) - np.repeat(starts, counts)
        keep = np.empty(len(rows_sorted), dtype=bool)
        keep[rand_order] = rank_in_entity < cap   # rank is position in
        # rand_order space: row rand_order[i] has within-entity random rank
        # rank_in_entity[i] because groups stay contiguous under lexsort
        over = counts > cap
        # weight rescale so the capped sample represents the full count
        # (reference: MinHeapWithFixedCapacity cumCount/size rescale,
        # RandomEffectDataSet.scala:325-388)
        weight_scale[over] = counts[over] / cap
        leftover = counts - np.minimum(counts, cap)
        lower = config.passive_data_lower_bound
        # leftovers of entities above the passive lower bound are passive
        # (scored, not trained on); at/below the bound they are discarded
        # (reference: RandomEffectDataSet.scala:399-446)
        passive_entities = (np.ones(E, dtype=bool) if lower is None
                            else leftover > lower)
        num_passive = int(leftover[passive_entities & over].sum())
        drop_mask = ~keep & ~passive_entities[grp]
        discarded_rows = rows_sorted[drop_mask]
        rows_sorted, grp = rows_sorted[keep], grp[keep]
        counts = np.bincount(grp, minlength=E)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])

    # --- lane order: count-descending, then pow2 S-buckets ---------------
    perm = np.argsort(-counts, kind="stable")        # lane -> uniq rank
    lane_of = np.empty(E, dtype=np.int64)
    lane_of[perm] = np.arange(E)                     # uniq rank -> lane
    counts_lane = counts[perm]
    entity_ids = uniq[perm]
    entity_position = np.full(dataset.num_entities(re_type), -1, dtype=np.int64)
    entity_position[entity_ids] = np.arange(E)

    pow2_lane = _ceil_pow2(counts_lane)
    # group adjacent power-of-two classes when there are more classes than
    # max_buckets (compile-count cap; padding cost shows in padding_stats)
    uniq_keys, key_of_lane = np.unique(pow2_lane, return_inverse=True)
    n_classes = len(uniq_keys)
    mb = config.max_buckets
    if mb is not None and n_classes > mb > 0:
        width = -(-n_classes // mb)
        key_of_lane = ((n_classes - 1) - key_of_lane) // width
    bucket_bounds = np.concatenate(
        [[0], np.flatnonzero(np.diff(key_of_lane)) + 1, [E]])

    # kept rows in (lane, canonical-row) order; per-lane slot index
    lane_rows = lane_of[grp]
    ord_lane = np.lexsort((rows_sorted, lane_rows))
    row_ids_l = rows_sorted[ord_lane]
    lane_l = lane_rows[ord_lane]
    lane_starts = np.concatenate([[0], np.cumsum(counts_lane)[:-1]])
    slot_l = np.arange(len(row_ids_l)) - np.repeat(lane_starts, counts_lane)

    # --- per-entity feature projection (index-map projector) --------------
    projection = None
    proj_matrix = None
    if config.projector == "index_map":
        # observed-column mask per entity: segmented any over kept rows
        # (uniq-rank order; reordered to lanes below).  Every entity keeps
        # >= 1 row after capping, so reduceat segments are never empty.
        ind = (x_flat[rows_sorted] != 0)
        obs = np.logical_or.reduceat(ind, starts)
        ratio = config.features_to_samples_ratio
        intercept_col = d_global - 1  # intercept-last convention (IndexMap)
        selected = obs
        if ratio is not None:
            selected = _pearson_select_segmented(
                x_flat, y_flat, rows_sorted, starts, counts, obs, ratio,
                intercept_col, w_flat)
        # ragged column lists -> [E, d_local] padded index array, columns
        # ascending per entity (np.nonzero yields row-major order)
        sel_lane = selected[perm]
        e_idx, col_idx = np.nonzero(sel_lane)
        per_entity = np.bincount(e_idx, minlength=E)
        d_local = int(per_entity.max()) if len(e_idx) else 1
        pos = np.arange(len(col_idx)) - np.repeat(
            np.concatenate([[0], np.cumsum(per_entity)[:-1]]), per_entity)
        projection = np.full((E, max(d_local, 1)), -1, dtype=np.int64)
        projection[e_idx, pos] = col_idx
    elif config.projector.startswith("random_projection:"):
        # Gaussian random projection shared across entities (reference:
        # ProjectionMatrixBroadcast.buildRandomProjectionBroadcastProjector +
        # ProjectionMatrix.buildGaussianRandomProjectionMatrix, scala:95-125);
        # the intercept column survives projection via the extra selector row
        k = int(config.projector.split(":", 1)[1])
        from photon_ml_tpu.parallel.factored import gaussian_projection_matrix
        proj_matrix = np.asarray(gaussian_projection_matrix(
            k, d_global, keep_intercept=True, seed=config.seed), dtype=dtype)
    elif config.projector != "identity":
        raise ValueError(f"unknown projector {config.projector!r} (expected "
                         "'index_map', 'identity', or 'random_projection:<k>')")

    # --- assemble buckets -------------------------------------------------
    # blocks assemble on the host and transfer asynchronously (jnp.asarray
    # starts the DMA immediately).  A device-side gather from the flat
    # shard was tried and measured NET NEGATIVE over the tunneled device:
    # it removed ~half the bytes but added 8 gather programs whose
    # per-process executable uploads cost more than the transfer saved
    # (program count, not bytes, is the scarce resource there).
    if not _is_np_dense(dataset.feature_shards[config.feature_shard]):
        raise TypeError(
            f"random-effect shard {config.feature_shard!r} must be a dense "
            "array (sparse per-entity shards would gather ragged columns); "
            "project or densify it at ingest")
    buckets = []
    num_active = len(row_ids_l)
    in_bucket_of_lane = np.searchsorted(bucket_bounds, lane_l, side="right") - 1
    # pad-row/pad-column trick: one zero row (and, for the index-map
    # projector, one zero column) appended to the flat arrays lets padding
    # ids gather ZEROS directly — no [E, S, d]-sized mask multiplies, which
    # dominated this build at MovieLens-20M scale (measured ~40% of 12s)
    d_pad = d_global + (1 if projection is not None else 0)
    x_pad = np.zeros((n + 1, d_pad), x_flat.dtype)  # one copy, final shape
    x_pad[:n, :d_global] = x_flat
    y_pad = np.concatenate([y_flat, [_SAFE_LABEL]]).astype(dtype)
    w_pad = (None if w_flat is None
             else np.concatenate([w_flat, [0.0]]).astype(dtype))
    o_pad = (None if o_flat is None
             else np.concatenate([o_flat, [0.0]]).astype(dtype))
    for b in range(len(bucket_bounds) - 1):
        lb, ub = int(bucket_bounds[b]), int(bucket_bounds[b + 1])
        Eb = ub - lb
        Sb = int(counts_lane[lb:ub].max()) if Eb else 1
        sel = in_bucket_of_lane == b
        r_ids = np.full((Eb, max(Sb, 1)), -1, dtype=np.int64)
        r_ids[lane_l[sel] - lb, slot_l[sel]] = row_ids_l[sel]
        mask = (r_ids >= 0).astype(dtype)
        gat = np.where(r_ids >= 0, r_ids, n)  # pad cell -> zero row

        if projection is not None:
            cols = projection[lb:ub]
            gcols = np.where(cols >= 0, cols, x_flat.shape[1])  # -> zero col
            xb = x_pad[gat[:, :, None], gcols[:, None, :]]
        elif proj_matrix is not None:
            xb = np.einsum("esd,kd->esk", x_pad[gat], proj_matrix)
        else:
            xb = x_pad[gat]

        labels = y_pad[gat]
        # both the mask and gathered weights are already 0 at padding cells
        weights = ((w_pad[gat] if w_pad is not None else mask)
                   * weight_scale[perm[lb:ub], None])
        offsets = None if o_pad is None else o_pad[gat]
        host = EntityBlocks(x=xb, labels=labels, mask=mask, weights=weights,
                            offsets=offsets)
        if config.keep_host_blocks:
            # out-of-core build: the numpy blocks ARE the source of truth;
            # device copies materialize lazily and can be evicted/re-streamed
            buckets.append(EntityBucket(lane_start=lb, row_ids=r_ids,
                                        host_blocks=host))
        else:
            # resident build: transfer eagerly (jnp.asarray starts the DMA
            # immediately) and let the numpy staging arrays free
            buckets.append(EntityBucket(
                lane_start=lb, row_ids=r_ids, host_blocks=None,
                _blocks=EntityBlocks(
                    x=jnp.asarray(xb), labels=jnp.asarray(labels),
                    mask=jnp.asarray(mask), weights=jnp.asarray(weights),
                    offsets=None if offsets is None
                    else jnp.asarray(offsets))))

    return RandomEffectDataset(
        config=config, buckets=buckets, entity_ids=entity_ids,
        entity_position=entity_position,
        projection=projection, global_dim=d_global,
        num_active=num_active, num_passive=num_passive,
        discarded_rows=discarded_rows, projection_matrix=proj_matrix)


def _pearson_select_segmented(
    x_flat: np.ndarray,
    y_flat: np.ndarray,
    rows_sorted: np.ndarray,
    starts: np.ndarray,
    counts: np.ndarray,
    obs: np.ndarray,
    ratio: float,
    intercept_col: int,
    w_flat: Optional[np.ndarray],
) -> np.ndarray:
    """Per-entity Pearson feature selection, all entities at once.

    For entities whose observed-column count exceeds ratio * num_samples,
    keep the ceil(ratio * num_samples) columns with the largest |corr(x, y)|
    (the intercept always survives).  reference: LocalDataSet
    .filterFeaturesByPearsonCorrelationScore (scala:135, 221-288).
    Segment sums give per-entity moments; one argsort along the column axis
    ranks every entity's columns simultaneously.
    """
    del w_flat  # reference Pearson is unweighted
    E, d = obs.shape
    xs = x_flat[rows_sorted]
    ys = y_flat[rows_sorted]
    ne = np.maximum(counts, 1).astype(np.float64)[:, None]
    sum_x = np.add.reduceat(xs, starts, axis=0)
    sum_x2 = np.add.reduceat(xs * xs, starts, axis=0)
    sum_xy = np.add.reduceat(xs * ys[:, None], starts, axis=0)
    sum_y = np.add.reduceat(ys, starts)[:, None]
    sum_y2 = np.add.reduceat(ys * ys, starts)[:, None]
    cov = sum_xy - sum_x * sum_y / ne
    var_x = np.maximum(sum_x2 - sum_x * sum_x / ne, 0.0)
    var_y = np.maximum(sum_y2 - sum_y * sum_y / ne, 0.0)
    denom = np.sqrt(var_x * var_y)
    corr = np.where(denom > 0, np.abs(cov) / np.where(denom > 0, denom, 1.0), 0.0)

    target = np.ceil(ratio * np.maximum(counts, 1)).astype(np.int64)
    needs = obs.sum(axis=1) > ratio * np.maximum(counts, 1)
    has_int = obs[:, intercept_col]
    # rank candidate (observed, non-intercept) columns by -corr, stable
    score = np.where(obs, corr, -np.inf)
    score[:, intercept_col] = -np.inf
    col_order = np.argsort(-score, axis=1, kind="stable")
    ranks = np.empty_like(col_order)
    np.put_along_axis(ranks, col_order, np.arange(d)[None, :], axis=1)
    keep_n = np.maximum(target - has_int.astype(np.int64), 1)
    chosen = obs & (ranks < keep_n[:, None])
    chosen[:, intercept_col] = has_int
    return np.where(needs[:, None], chosen, obs)
