"""Dataset -> device-block builders: the shuffle work, done once at prep time.

Rebuild of the reference's per-coordinate dataset machinery:
  - FixedEffectDataSet (photon-api/.../data/FixedEffectDataSet.scala:30-148)
  - RandomEffectDataSet build: group-by-entity, per-entity sample cap with
    weight rescaling, passive data, feature selection
    (photon-api/.../data/RandomEffectDataSet.scala:240-472)
  - LocalDataSet feature filtering (Pearson), local sampling
    (photon-api/.../data/LocalDataSet.scala:36-321)
  - IndexMapProjector: per-entity dense local feature space
    (photon-api/.../projector/IndexMapProjectorRDD.scala:32-208)
  - RandomEffectDataConfiguration / FixedEffectDataConfiguration
    (photon-api/.../data/{RandomEffect,FixedEffect}DataConfiguration.scala)

Where the reference shuffles (groupByKey by REId, MinHeap combineByKey for
the reservoir cap) every time a dataset is built on the cluster, here the
grouping/capping/projection run once on host numpy and emit static device
blocks; the training loop touches only dense arrays after this point.
"""
from __future__ import annotations

import dataclasses
import weakref
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.parallel.random_effect import EntityBlocks

_SAFE_LABEL = 0.5  # valid for every loss family; see pad_batch_to_mesh


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfig:
    """reference: FixedEffectDataConfiguration.scala (featureShardId; the
    minNumPartitions knob is meaningless here — sharding is the mesh's)."""

    feature_shard: str


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfig:
    """reference: RandomEffectDataConfiguration.scala:42-140.
    `active_data_upper_bound` caps per-entity samples (reservoir-style, with
    weight rescaling); rows beyond the cap become passive data (scored, not
    trained on) when the entity has more than `passive_data_lower_bound`
    rows.  `features_to_samples_ratio` triggers per-entity Pearson feature
    selection.  `projector` in {"index_map", "identity"}."""

    random_effect_type: str
    feature_shard: str
    active_data_upper_bound: Optional[int] = None
    passive_data_lower_bound: Optional[int] = None
    features_to_samples_ratio: Optional[float] = None
    # "index_map" | "identity" | "random_projection:<k>"
    # (reference: ProjectorType.scala — IndexMapProjection, IdentityProjection,
    # RandomProjection(dim))
    projector: str = "index_map"
    seed: int = 7


@dataclasses.dataclass
class FixedEffectDataset:
    """Flat [n] arrays for one shard, canonical row order."""

    x: np.ndarray
    labels: np.ndarray
    weights: Optional[np.ndarray]
    offsets: Optional[np.ndarray]
    feature_shard: str

    @staticmethod
    def build(dataset: GameDataset, config: FixedEffectDataConfig) -> "FixedEffectDataset":
        return FixedEffectDataset(
            x=dataset.feature_shards[config.feature_shard],
            labels=dataset.response,
            weights=dataset.weights,
            offsets=dataset.offsets,
            feature_shard=config.feature_shard)


def _pearson_select(x: np.ndarray, y: np.ndarray, keep: int) -> np.ndarray:
    """Top-`keep` columns by |Pearson correlation with the label|; constant
    columns (e.g. the intercept) score epsilon but are ranked last only among
    themselves — the intercept is re-added by the caller.
    reference: LocalDataSet.computePearsonCorrelationScore (line 221-288)."""
    xc = x - x.mean(axis=0, keepdims=True)
    yc = y - y.mean()
    sx = np.sqrt((xc * xc).sum(axis=0))
    sy = np.sqrt((yc * yc).sum())
    denom = sx * sy
    corr = np.where(denom > 0, np.abs(xc.T @ yc) / np.where(denom > 0, denom, 1.0), 0.0)
    return np.argsort(-corr, kind="stable")[:keep]


@dataclasses.dataclass
class RandomEffectDataset:
    """Per-entity training blocks + the index plumbing to score flat rows.

    reference: RandomEffectDataSet (activeData + uniqueId->REId map +
    passiveData) — here the "joins" are materialized index arrays:
      - entity_position[v]: vocab entity v -> block lane (-1 if unseen)
      - active_row_ids[e, s]: block cell -> canonical row id (-1 pad), which
        also realizes addScoresToOffsets as one gather
    """

    config: RandomEffectDataConfig
    blocks: EntityBlocks
    entity_ids: np.ndarray          # [E] vocab indices, block lane order
    entity_position: np.ndarray     # [V] vocab index -> block lane or -1
    active_row_ids: np.ndarray      # [E, S] canonical row ids, -1 = padding
    projection: Optional[np.ndarray]  # [E, d_local] global col ids, -1 pad
    global_dim: int
    num_active: int
    num_passive: int
    # dense Gaussian random-projection matrix [d_local, d_global], shared by
    # all entities (reference: ProjectionMatrixBroadcast) — exclusive with
    # the per-entity index `projection`
    projection_matrix: Optional[np.ndarray] = None
    # canonical rows capped out of entities whose LEFTOVER count is at/below
    # passive_data_lower_bound: DISCARDED, not scored (reference:
    # RandomEffectDataSet.scala:399-446 keeps passive data only for entities
    # whose passive count exceeds the bound) — flat_entity_lanes maps them to
    # lane -1 so they contribute score 0, the missing-score default.
    discarded_rows: Optional[np.ndarray] = None  # [k] canonical row ids

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)

    @property
    def local_dim(self) -> int:
        return self.blocks.dim

    def with_offsets_from_flat(self, flat_offsets) -> EntityBlocks:
        """addScoresToOffsets (reference: RandomEffectDataSet.scala:68-88):
        gather the canonical-order offset vector into block layout."""
        flat = jnp.asarray(flat_offsets)
        safe = jnp.maximum(jnp.asarray(self.active_row_ids), 0)
        off = flat[safe] * jnp.asarray(self.blocks.mask)
        return self.blocks.with_offsets(off.astype(self.blocks.x.dtype))

    def scatter_to_global(self, local_coefficients) -> jnp.ndarray:
        """[E, d_local] local-space coefficients -> [E, d_global]
        (reference: IndexMapProjector.projectCoefficients /
        ProjectionMatrix.projectCoefficients = P^T c)."""
        if self.projection_matrix is not None:
            return jnp.asarray(local_coefficients) @ jnp.asarray(self.projection_matrix)
        from photon_ml_tpu.parallel.random_effect import scatter_local_to_global
        return scatter_local_to_global(jnp.asarray(local_coefficients),
                                       self.projection, self.global_dim)

    def flat_entity_lanes(self, entity_index: np.ndarray) -> np.ndarray:
        """Map a canonical-order entity-index column to block lanes.
        Discarded rows (capped out of below-bound entities) get lane -1."""
        idx = np.asarray(entity_index)
        lanes = np.full_like(idx, -1)
        valid = idx >= 0
        lanes[valid] = self.entity_position[idx[valid]]
        if self.discarded_rows is not None and len(self.discarded_rows):
            lanes[self.discarded_rows] = -1
        return lanes


# (dataset -> {(config, dtype) -> built blocks}) memo: grid sweeps and
# hyperparameter tuning refit the same data under many lambdas — the blocks
# depend only on (data, config, seed), never on the lambdas being searched
_BUILD_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def build_random_effect_dataset(
    dataset: GameDataset,
    config: RandomEffectDataConfig,
    dtype=np.float64,
) -> RandomEffectDataset:
    """Group-by-entity -> cap -> select features -> project -> pad.
    Memoized per (dataset, config, dtype) — see _BUILD_CACHE.

    reference call path: RandomEffectDataSet.apply (scala:240-277) +
    featureSelectionOnActiveData (scala:457-471) +
    RandomEffectDataSetInProjectedSpace.buildWithProjectorType."""
    per_ds = _BUILD_CACHE.setdefault(dataset, {})
    key = (config, np.dtype(dtype).name)
    if key in per_ds:
        return per_ds[key]
    built = _build_random_effect_dataset(dataset, config, dtype)
    per_ds[key] = built
    return built


def _build_random_effect_dataset(
    dataset: GameDataset,
    config: RandomEffectDataConfig,
    dtype,
) -> RandomEffectDataset:
    re_type = config.random_effect_type
    x_flat = np.asarray(dataset.feature_shards[config.feature_shard], dtype=dtype)
    y_flat = np.asarray(dataset.response, dtype=dtype)
    w_flat = None if dataset.weights is None else np.asarray(dataset.weights, dtype)
    o_flat = None if dataset.offsets is None else np.asarray(dataset.offsets, dtype)
    ent = np.asarray(dataset.entity_indices[re_type])
    n, d_global = x_flat.shape
    rng = np.random.default_rng(config.seed)

    present = ent >= 0
    uniq = np.unique(ent[present])
    E = len(uniq)
    entity_position = np.full(dataset.num_entities(re_type), -1, dtype=np.int64)
    entity_position[uniq] = np.arange(E)

    # group rows per entity (one argsort — the groupByKey replacement)
    order = np.argsort(ent[present], kind="stable")
    rows_present = np.nonzero(present)[0][order]
    counts = np.bincount(entity_position[ent[present]], minlength=E)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])

    cap = config.active_data_upper_bound
    num_passive = 0
    active_rows_per_entity = []
    discarded: list[np.ndarray] = []
    weight_scale = np.ones(E)
    for e in range(E):
        rows_e = rows_present[starts[e]: starts[e] + counts[e]]
        if cap is not None and len(rows_e) > cap:
            keep = rng.choice(len(rows_e), size=cap, replace=False)
            lower = config.passive_data_lower_bound
            leftover_count = len(rows_e) - cap
            if lower is None or leftover_count > lower:
                num_passive += leftover_count
            else:
                # below-bound leftovers are discarded, not scored
                # (reference: RandomEffectDataSet.scala:399-446)
                leftover = np.setdiff1d(np.arange(len(rows_e)), keep)
                discarded.append(rows_e[leftover])
            # weight rescale so the capped sample represents the full count
            # (reference: MinHeapWithFixedCapacity cumCount/size rescale,
            # RandomEffectDataSet.scala:325-388)
            weight_scale[e] = len(rows_e) / cap
            rows_e = rows_e[np.sort(keep)]
        active_rows_per_entity.append(rows_e)
    discarded_rows = (np.concatenate(discarded) if discarded
                      else np.zeros((0,), dtype=np.int64))

    S = max((len(r) for r in active_rows_per_entity), default=1)
    active_row_ids = np.full((E, S), -1, dtype=np.int64)
    for e, rows_e in enumerate(active_rows_per_entity):
        active_row_ids[e, : len(rows_e)] = rows_e
    mask = (active_row_ids >= 0).astype(dtype)
    safe_ids = np.maximum(active_row_ids, 0)

    # per-entity feature projection (index-map projector): observed columns
    projection = None
    proj_matrix = None
    if config.projector == "index_map":
        col_lists = []
        ratio = config.features_to_samples_ratio
        intercept_col = d_global - 1  # intercept-last convention (IndexMap)
        for e, rows_e in enumerate(active_rows_per_entity):
            observed = np.nonzero(np.any(x_flat[rows_e] != 0, axis=0))[0]
            if ratio is not None and len(observed) > ratio * max(len(rows_e), 1):
                keep = int(np.ceil(ratio * max(len(rows_e), 1)))
                has_intercept = intercept_col in observed
                cand = observed[observed != intercept_col] if has_intercept else observed
                sel = _pearson_select(x_flat[rows_e][:, cand], y_flat[rows_e],
                                      max(keep - int(has_intercept), 1))
                chosen = cand[sel]
                if has_intercept:  # the intercept always survives selection
                    chosen = np.concatenate([chosen, [intercept_col]])
                observed = np.sort(chosen)
            col_lists.append(observed)
        d_local = max((len(c) for c in col_lists), default=1)
        projection = np.full((E, d_local), -1, dtype=np.int64)
        for e, colse in enumerate(col_lists):
            projection[e, : len(colse)] = colse
        # gather features into local spaces: x_blocks[e, s, j] = x[row, proj[e, j]]
        x_blocks = np.zeros((E, S, d_local), dtype=dtype)
        for e in range(E):
            cols = projection[e]
            valid_cols = cols >= 0
            x_blocks[e][:, valid_cols] = x_flat[safe_ids[e]][:, cols[valid_cols]]
        x_blocks *= mask[:, :, None]
    elif config.projector == "identity":
        x_blocks = x_flat[safe_ids] * mask[:, :, None]
    elif config.projector.startswith("random_projection:"):
        # Gaussian random projection shared across entities (reference:
        # ProjectionMatrixBroadcast.buildRandomProjectionBroadcastProjector +
        # ProjectionMatrix.buildGaussianRandomProjectionMatrix, scala:95-125);
        # the intercept column survives projection via the extra selector row
        k = int(config.projector.split(":", 1)[1])
        from photon_ml_tpu.parallel.factored import gaussian_projection_matrix
        proj_matrix = np.asarray(gaussian_projection_matrix(
            k, d_global, keep_intercept=True, seed=config.seed), dtype=dtype)
        x_blocks = np.einsum("esd,kd->esk", x_flat[safe_ids] * mask[:, :, None],
                             proj_matrix)
    else:
        raise ValueError(f"unknown projector {config.projector!r} (expected "
                         "'index_map', 'identity', or 'random_projection:<k>')")

    labels = np.where(mask > 0, y_flat[safe_ids], _SAFE_LABEL)
    weights = (w_flat[safe_ids] if w_flat is not None else np.ones((E, S), dtype))
    weights = weights * mask * weight_scale[:, None]
    offsets = None if o_flat is None else o_flat[safe_ids] * mask

    blocks = EntityBlocks(
        x=jnp.asarray(x_blocks), labels=jnp.asarray(labels),
        mask=jnp.asarray(mask), weights=jnp.asarray(weights),
        offsets=None if offsets is None else jnp.asarray(offsets))
    return RandomEffectDataset(
        config=config, blocks=blocks, entity_ids=uniq,
        entity_position=entity_position, active_row_ids=active_row_ids,
        projection=projection, global_dim=d_global,
        num_active=int(mask.sum()), num_passive=num_passive,
        discarded_rows=discarded_rows, projection_matrix=proj_matrix)
