"""GAME dataset: struct-of-arrays with a fixed canonical row order.

Rebuild of the reference's data containers:
  - GameDatum (photon-lib/.../data/GameDatum.scala:38-70): per-row
    (response, offset, weight, per-shard features, id tags)
  - GameConverters (photon-api/.../data/GameConverters.scala:29-171):
    DataFrame -> RDD[(uid, GameDatum)] with monotonically_increasing_id
  - FixedEffectDataSet (photon-api/.../data/FixedEffectDataSet.scala:30-148)
  - InputColumnsNames (photon-api/.../data/InputColumnsNames.scala)

Key TPU design decision (SURVEY §7 "Score bookkeeping"): the uid IS the row
position.  Every coordinate keeps its scores as a dense [n] device array in
this canonical order, so CoordinateDescent's add/subtract-scores joins
(reference: DataScores +/- via full outer joins, CoordinateDataScores
.scala:38-61) become elementwise array ops.  Entity membership per random
effect type is materialized once at ingest as an int index column
(`entity_index[re_type][row]`), which turns every keyBy(REId) shuffle of the
reference into a static gather.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap


def _is_sparse(x) -> bool:
    try:
        import scipy.sparse as sp
        return sp.issparse(x)
    except ImportError:  # pragma: no cover
        return False


@dataclasses.dataclass(frozen=True)
class ReleasedHostShard:
    """Placeholder left in GameDataset.feature_shards after
    release_host_shard: keeps the shape/dtype metadata (shard_dim, byte
    accounting) while making accidental array reads fail loudly instead of
    silently operating on stale data."""

    shape: tuple
    dtype: np.dtype
    nbytes: int

    def __array__(self, *a, **kw):
        raise ValueError("this host shard was released "
                         "(GameDataset.release_host_shard); only the device "
                         "copy survives")


@dataclasses.dataclass
class InputColumnNames:
    """Remappable input column names (reference: InputColumnsNames.scala)."""

    response: str = "response"
    offset: str = "offset"
    weight: str = "weight"
    uid: str = "uid"


@dataclasses.dataclass(eq=False)  # identity semantics: holds arrays, and the
# RE-dataset build memo (data/batching.py) weak-keys on dataset identity
class GameDataset:
    """n rows in canonical order; everything else hangs off row position."""

    response: np.ndarray                       # [n] float
    feature_shards: Dict[str, np.ndarray]      # shard -> [n, d_shard] float
    offsets: Optional[np.ndarray] = None       # [n]
    weights: Optional[np.ndarray] = None       # [n]
    # re_type -> [n] int index into entity_vocabs[re_type]; -1 = missing id
    entity_indices: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    # re_type -> [num_entities] entity id strings (row i of a RandomEffect
    # model belongs to entity_vocabs[re_type][i])
    entity_vocabs: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    index_maps: Dict[str, IndexMap] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        n = len(self.response)
        for shard, x in self.feature_shards.items():
            if x.shape[0] != n:
                raise ValueError(f"shard {shard!r} has {x.shape[0]} rows, expected {n}")
        for re_type, idx in self.entity_indices.items():
            if len(idx) != n:
                raise ValueError(f"entity index {re_type!r} has {len(idx)} rows, expected {n}")

    # device copies of feature shards, transferred ONCE per dataset and
    # shared by every consumer (coordinate scoring, validation rescoring,
    # per-entity block gathers): over a slow host->device link a duplicate
    # shard transfer costs seconds, and validation rescoring runs every
    # coordinate update
    _device_shards: Dict[str, object] = dataclasses.field(
        default_factory=dict, repr=False, compare=False)
    # scoring-side memos (entity-lane maps etc.), keyed by consumer
    _scoring_cache: Dict[object, object] = dataclasses.field(
        default_factory=dict, repr=False, compare=False)

    def device_shard(self, shard: str, *, release_host: bool = False):
        """Device FeatureMatrix view of a shard (dense -> jnp array, scipy
        sparse -> PaddedSparse), built once and shared.

        NOTE the memory doubling: the host numpy shard and the device copy
        both stay alive for the whole fit (every byte of feature data
        exists twice).  `release_host=True` drops the host copy once the
        device copy exists — safe ONLY when nothing will re-read the host
        array (no out-of-core re-streaming, no dataset.subset, no stats);
        resident single-fit jobs qualify.  Streaming mode does the inverse
        (release_device_shard): chunks stage from the host copy and a full
        device copy would defeat the HBM budget."""
        if shard not in self._device_shards:
            from photon_ml_tpu.ops.features import as_feature_matrix
            host = self.feature_shards[shard]
            if isinstance(host, ReleasedHostShard):
                raise ValueError(
                    f"host shard {shard!r} was released (release_host_shard) "
                    "and no device copy survives; rebuild the dataset")
            self._device_shards[shard] = as_feature_matrix(host)
        if release_host:
            self.release_host_shard(shard)
        return self._device_shards[shard]

    def release_host_shard(self, shard: str) -> None:
        """Drop the host numpy copy of a shard, keeping only the device
        copy (halves the footprint of `device_shard`'s doubling).  The slot
        keeps a shape/dtype placeholder so shard_dim etc. still answer;
        array reads raise via device_shard's guard."""
        host = self.feature_shards.get(shard)
        if host is None or isinstance(host, ReleasedHostShard):
            return
        if shard not in self._device_shards:
            raise ValueError(f"no device copy of shard {shard!r} exists yet; "
                             "releasing the host copy would lose the data")
        self.feature_shards[shard] = ReleasedHostShard(
            shape=tuple(host.shape), dtype=np.dtype(getattr(host, "dtype",
                                                            np.float64)),
            nbytes=int(getattr(host, "nbytes", 0) or
                       getattr(host, "data", np.empty(0)).nbytes))

    def release_device_shard(self, shard: str) -> None:
        """Drop the shared device copy of a shard (the host copy remains
        the source of truth).  Used by streaming mode's staging path and by
        the coordinate residency manager's eviction rotation."""
        self._device_shards.pop(shard, None)

    @property
    def num_rows(self) -> int:
        return len(self.response)

    def num_entities(self, re_type: str) -> int:
        return len(self.entity_vocabs[re_type])

    def shard_dim(self, shard: str) -> int:
        return self.feature_shards[shard].shape[1]

    def process_slice(self, count: int = None,
                      index: int = None) -> "GameDataset":
        """THIS process's contiguous 1/P row block of the dataset (count/
        index default to the multihost runtime's identity) — the
        process-slice view a multi-host ingest uses so each host holds only
        the rows its mesh devices own.  Vocabularies and index maps are
        SHARED with the parent (every process sees identical global entity
        spaces, whatever rows it holds)."""
        from photon_ml_tpu.parallel.multihost import process_row_range
        r = process_row_range(self.num_rows, count=count, index=index)
        return self.subset(np.arange(r.start, r.stop))

    def subset(self, rows: np.ndarray) -> "GameDataset":
        """Row slice sharing vocabularies (for train/validation splits)."""
        take = lambda a: None if a is None else a[rows]
        return GameDataset(
            response=self.response[rows],
            feature_shards={s: x[rows] for s, x in self.feature_shards.items()},
            offsets=take(self.offsets),
            weights=take(self.weights),
            entity_indices={t: idx[rows] for t, idx in self.entity_indices.items()},
            entity_vocabs=self.entity_vocabs,
            index_maps=self.index_maps,
        )


def save_game_dataset(dataset: GameDataset, path: str) -> None:
    """Columnar npz persistence of a GameDataset (role of the reference's
    Avro input files once converted; see data/avro_io.py for Avro itself)."""
    arrays = {"response": dataset.response}
    if dataset.offsets is not None:
        arrays["offsets"] = dataset.offsets
    if dataset.weights is not None:
        arrays["weights"] = dataset.weights
    for s, x in dataset.feature_shards.items():
        if _is_sparse(x):
            if "::" in s:
                raise ValueError(
                    f"sparse shard name {s!r} may not contain '::' (it is "
                    "the npz key delimiter)")
            csr = x.tocsr()
            arrays[f"spshard::{s}::data"] = csr.data
            arrays[f"spshard::{s}::indices"] = csr.indices
            arrays[f"spshard::{s}::indptr"] = csr.indptr
            arrays[f"spshard::{s}::shape"] = np.asarray(csr.shape)
        else:
            arrays[f"shard::{s}"] = x
    for t, idx in dataset.entity_indices.items():
        arrays[f"entidx::{t}"] = idx
        arrays[f"entvocab::{t}"] = np.asarray(dataset.entity_vocabs[t]).astype(object)
    np.savez_compressed(path if path.endswith(".npz") else path + ".npz", **arrays)


def load_game_dataset(path: str) -> GameDataset:
    z = np.load(path if path.endswith(".npz") else path + ".npz",
                allow_pickle=True)
    shards, entidx, entvocab = {}, {}, {}
    sp_names = {k.split("::")[1] for k in z.files if k.startswith("spshard::")}
    for s in sp_names:
        import scipy.sparse as sp
        shards[s] = sp.csr_matrix(
            (z[f"spshard::{s}::data"], z[f"spshard::{s}::indices"],
             z[f"spshard::{s}::indptr"]),
            shape=tuple(z[f"spshard::{s}::shape"]))
    for k in z.files:
        if k.startswith("shard::"):
            shards[k[7:]] = z[k]
        elif k.startswith("entidx::"):
            entidx[k[8:]] = z[k]
        elif k.startswith("entvocab::"):
            entvocab[k[10:]] = z[k]
    return GameDataset(
        response=z["response"],
        feature_shards=shards,
        offsets=z["offsets"] if "offsets" in z.files else None,
        weights=z["weights"] if "weights" in z.files else None,
        entity_indices=entidx,
        entity_vocabs=entvocab)


def build_game_dataset(
    response: np.ndarray,
    feature_shards: Dict[str, np.ndarray],
    *,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    entity_ids: Optional[Dict[str, np.ndarray]] = None,
    entity_vocabs: Optional[Dict[str, np.ndarray]] = None,
    index_maps: Optional[Dict[str, IndexMap]] = None,
) -> GameDataset:
    """GameConverters equivalent: raw id columns -> indexed entity columns.

    `entity_ids[re_type]` is a [n] array of raw ids (strings/ints); ids are
    interned into a vocabulary (sorted for determinism) unless a shared
    vocab is supplied (scoring against a trained model's entity space, where
    unseen ids must map to -1 — the reference's passive/missing-score path).
    """
    entity_indices, vocabs = {}, {}
    for re_type, ids in (entity_ids or {}).items():
        ids = np.asarray(ids)
        if entity_vocabs and re_type in entity_vocabs:
            vocab = np.asarray(entity_vocabs[re_type])
            lookup = {v: i for i, v in enumerate(vocab.tolist())}
            idx = np.asarray([lookup.get(v, -1) for v in ids.tolist()],
                             dtype=np.int32)
        else:
            vocab, idx = np.unique(ids, return_inverse=True)
            idx = idx.astype(np.int32)
        entity_indices[re_type] = idx
        vocabs[re_type] = vocab
    return GameDataset(
        response=np.asarray(response, dtype=np.float64),
        # scipy.sparse shards stay sparse, canonicalized to CSR (row
        # slicing for subset/validation; the wide fixed-effect regime,
        # reference: AvroDataReader SparseVector columns); np.asarray on
        # them would produce a useless 0-d object array
        feature_shards={s: (x.tocsr() if _is_sparse(x) else np.asarray(x))
                        for s, x in feature_shards.items()},
        offsets=None if offsets is None else np.asarray(offsets, dtype=np.float64),
        weights=None if weights is None else np.asarray(weights, dtype=np.float64),
        entity_indices=entity_indices,
        entity_vocabs=vocabs,
        index_maps=index_maps or {},
    )
