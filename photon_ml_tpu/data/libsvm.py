"""LIBSVM text reader.

reference: photon-client/.../io/deprecated/LibSVMInputDataFormat.scala (legacy
LIBSVM -> RDD[LabeledPoint]) and dev-scripts/libsvm_text_to_trainingexample_avro.py
(the a1a conversion path in the reference README's "Try It Out!").

Returns dense or scipy-CSR host arrays; densify is the right call for
a1a-scale d (123 features) where the TPU wants one [n, d] matmul."""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def read_libsvm(
    path: str,
    num_features: Optional[int] = None,
    add_intercept: bool = True,
    zero_based: bool = False,
    binary_labels_to_01: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """-> (X [n, d(+1)], y [n]).  The intercept column (all ones) is appended
    LAST, matching IndexMap's intercept-last convention."""
    rows, cols, vals, labels = [], [], [], []
    max_col = -1
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break
                idx_s, _, val_s = tok.partition(":")
                j = int(idx_s) - (0 if zero_based else 1)
                if j < 0:
                    raise ValueError(
                        f"{path}: feature index {idx_s} on line {i + 1} is below "
                        f"the {'0' if zero_based else '1'}-based minimum "
                        "(pass zero_based=True for 0-based files)")
                rows.append(len(labels) - 1)
                cols.append(j)
                vals.append(float(val_s))
                max_col = max(max_col, j)
    n = len(labels)
    d = num_features if num_features is not None else max_col + 1
    if max_col >= d:
        raise ValueError(
            f"{path}: feature index {max_col} out of range for "
            f"num_features={d} (indices are {'0' if zero_based else '1'}-based)")
    x = np.zeros((n, d + (1 if add_intercept else 0)))
    x[np.asarray(rows, dtype=np.int64), np.asarray(cols, dtype=np.int64)] = vals
    if add_intercept:
        x[:, -1] = 1.0
    y = np.asarray(labels)
    if binary_labels_to_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y > 0).astype(np.float64)  # ±1 -> {0,1}, the API's label space
    return x, y
