"""Scoring CLI.

reference: GAME scoring driver (photon-client/.../cli/game/scoring/
Driver.scala:37-309): load model + data -> score -> save scores + optional
evaluation.

  python -m photon_ml_tpu.cli.score --model-dir out/best \
      --data test.npz --output scores.npz [--evaluators AUC,RMSE]
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-score")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--data", required=True, help=".npz GameDataset or .libsvm")
    p.add_argument("--output", required=True, help="scores .npz output path")
    p.add_argument("--evaluators", default=None)
    p.add_argument("--predict", action="store_true",
                   help="also emit mean predictions (inverse link)")
    p.add_argument("--mesh", default="auto",
                   help="'auto' = all local devices, 'none', or 'DxF'")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from photon_ml_tpu.cli.train import _load_dataset, make_mesh_from_arg
    from photon_ml_tpu.evaluation import parse_evaluator
    from photon_ml_tpu.models.io import load_game_model

    model, _config = load_game_model(args.model_dir)
    ds = _load_dataset(args.data, model.task_type)
    mesh = make_mesh_from_arg(args.mesh)
    scores = np.asarray(model.score_dataset(ds, mesh))
    out = {"scores": scores}
    if args.predict:
        out["predictions"] = np.asarray(model.predict(ds, mesh))
    np.savez_compressed(args.output if args.output.endswith(".npz")
                        else args.output + ".npz", **out)

    result = {"rows": int(ds.num_rows), "output": args.output,
              "evaluation": {}}
    if args.evaluators:
        total = scores + (ds.offsets if ds.offsets is not None else 0.0)
        for spec in args.evaluators.split(","):
            ev, group = parse_evaluator(spec)
            if group is not None:
                v = ev.evaluate_grouped(ds.entity_indices[group], total,
                                        ds.response, ds.weights)
            else:
                v = ev(total, ds.response, ds.weights)
            result["evaluation"][ev.name] = v
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
