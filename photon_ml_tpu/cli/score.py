"""Scoring CLI.

reference: GAME scoring driver (photon-client/.../cli/game/scoring/
Driver.scala:37-309): load model + data -> score -> save scores
(ScoringResultAvro) + optional evaluation.

  python -m photon_ml_tpu.cli.score --model-dir out/best \
      --data test.npz|test.avro --output scores[.npz|.avro]
      [--format npz|avro] [--evaluators AUC,RMSE]
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-score")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--data", required=True,
                   help=".npz GameDataset, .libsvm, or Avro input (file, "
                        "directory, or glob)")
    p.add_argument("--output", required=True, help="scores output path")
    p.add_argument("--format", default="npz", choices=["npz", "avro"],
                   help="score output format; avro writes ScoringResultAvro "
                        "records (reference: ScoreProcessingUtils)")
    p.add_argument("--model-id", default=None,
                   help="modelId stamped into ScoringResultAvro records "
                        "(default: the model directory name)")
    p.add_argument("--feature-shard-map", default=None,
                   help="Avro inputs: JSON (inline or @file) shard -> bags "
                        "merge map (see cli.train)")
    p.add_argument("--id-columns", default=None,
                   help="Avro inputs: comma-separated id tags to extract")
    p.add_argument("--input-columns", default=None,
                   help="Avro inputs: JSON remap of response/offset/weight/"
                        "uid column names (see cli.train)")
    p.add_argument("--evaluators", default=None)
    p.add_argument("--predict", action="store_true",
                   help="also emit mean predictions (inverse link); only "
                        "the npz output format carries them, so combining "
                        "with --format avro is an error")
    p.add_argument("--mesh", default="auto",
                   help="'auto' = all local devices, 'none', or 'DxF'")
    p.add_argument("--no-compile-cache", action="store_true",
                   help="disable the persistent XLA compilation cache")
    return p


def _load_scoring_data(args, model, model_dir):
    """Avro scoring input reads in the MODEL's feature/entity spaces
    (reference: the scoring driver resolves features through the trained
    model's index maps; unseen entities score through the fixed effect
    only).  Returns (dataset, uids or None)."""
    from photon_ml_tpu.cli.train import (_load_dataset, parse_feature_shard_map,
                                         parse_input_columns,
                                         resolve_avro_paths)
    avro_paths = resolve_avro_paths(args.data)
    if avro_paths is None:
        return _load_dataset(args.data, model.task_type), None
    from photon_ml_tpu.data.avro_game import read_game_examples
    from photon_ml_tpu.models.game import MatrixFactorizationModel
    from photon_ml_tpu.models.io import load_model_index_maps
    id_cols = [c for c in (args.id_columns or "").split(",") if c]
    entity_vocabs = {}

    def add_tag(tag, vocab):
        if tag is None:
            return
        entity_vocabs.setdefault(tag, np.asarray(vocab))
        if tag not in id_cols:
            id_cols.append(tag)

    for m in model.coordinates.values():
        if isinstance(m, MatrixFactorizationModel):
            add_tag(m.row_effect_type, m.row_ids)
            add_tag(m.col_effect_type, m.col_ids)
        elif getattr(m, "random_effect_type", None) is not None \
                and hasattr(m, "entity_ids"):
            add_tag(m.random_effect_type, m.entity_ids)
    index_maps = load_model_index_maps(model_dir)
    shard_map = parse_feature_shard_map(args.feature_shard_map)
    missing = sorted(set(shard_map) - set(index_maps or {}))
    if missing:
        # a PARTIALLY covered shard map is the same failure as no maps at
        # all: read_game_examples would scan a fresh vocabulary for the
        # uncovered shard and columns would silently misalign with the model
        raise SystemExit(
            f"model at {model_dir!r} records no saved index map for feature "
            f"shard(s) {missing} named in --feature-shard-map, so Avro "
            "scoring data cannot be resolved into the model's feature space "
            "(columns would silently misalign). Re-save the model with index "
            "maps for every shard, or score from an npz GameDataset instead.")
    result = read_game_examples(
        avro_paths, shard_map,
        id_columns=id_cols,
        columns=parse_input_columns(getattr(args, "input_columns", None)),
        index_maps=index_maps,
        entity_vocabs=entity_vocabs or None,
        require_response=False)
    return result.dataset, result.uids


def require_fully_labeled(ds, purpose: str) -> None:
    """Shared labeled-data gate for score/diagnose: ANY unlabeled row would
    silently NaN-poison metrics, so partial labels are an error too."""
    nan = np.isnan(np.asarray(ds.response))
    if nan.all():
        raise SystemExit(f"{purpose} requires labeled data (the input has "
                         "no response column)")
    if nan.any():
        raise SystemExit(
            f"{purpose} requires a response for every record; "
            f"{int(nan.sum())} of {ds.num_rows} rows are unlabeled")


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.predict and args.format == "avro":
        # ScoringResultAvro records have no prediction field; silently
        # dropping --predict hid the loss — fail loudly instead
        parser.error("--predict emits a predictions array that only the npz "
                     "output format carries; drop --predict or use "
                     "--format npz")

    from photon_ml_tpu.cli.train import make_mesh_from_arg
    from photon_ml_tpu.evaluation import parse_evaluator
    from photon_ml_tpu.models.io import load_game_model
    from photon_ml_tpu.utils.jax_cache import (CompileTimeTracker,
                                               enable_persistent_cache)

    compile_tracker = CompileTimeTracker().install()
    if not args.no_compile_cache:
        enable_persistent_cache()

    model, _config = load_game_model(args.model_dir)
    ds, uids = _load_scoring_data(args, model, args.model_dir)
    mesh = make_mesh_from_arg(args.mesh)
    scores = np.asarray(model.score_dataset(ds, mesh))

    has_response = not np.isnan(np.asarray(ds.response)).all()
    if args.format == "avro":
        from photon_ml_tpu.data.avro_io import write_scores_avro
        import os
        out_path = (args.output if args.output.endswith(".avro")
                    else args.output + ".avro")
        model_id = args.model_id or os.path.basename(
            args.model_dir.rstrip("/")) or "model"
        write_scores_avro(out_path, model_id, scores,
                          labels=ds.response if has_response else None,
                          weights=ds.weights, uids=uids)
    else:
        out = {"scores": scores}
        if args.predict:
            out["predictions"] = np.asarray(model.predict(ds, mesh))
        np.savez_compressed(args.output if args.output.endswith(".npz")
                            else args.output + ".npz", **out)

    result = {"rows": int(ds.num_rows), "output": args.output,
              "format": args.format,
              "compile_s": round(compile_tracker.seconds, 2),
              "evaluation": {}}
    if args.evaluators:
        require_fully_labeled(ds, "--evaluators")
        total = scores + (ds.offsets if ds.offsets is not None else 0.0)
        for spec in args.evaluators.split(","):
            ev, group = parse_evaluator(spec)
            if group is not None:
                v = ev.evaluate_grouped(ds.entity_indices[group], total,
                                        ds.response, ds.weights)
            else:
                v = ev(total, ds.response, ds.weights)
            result["evaluation"][ev.name] = v
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
