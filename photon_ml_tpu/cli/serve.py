"""Online scoring service CLI.

The serving counterpart of cli.score: load a GAME model directory (any
layout `models/io.py` reads — npz, Avro interchange, or a directory the
Scala reference wrote) into a warmed `ScoringService` and serve it.

HTTP mode (default) — a dependency-free stdlib server:

  python -m photon_ml_tpu.cli.serve --model-dir out/best --port 8080

  POST /score    {"features": {shard: [[...]]}, "ids": {type: [...]},
                  "timeout_ms": 50}        -> {"scores": [...]}
  POST /predict  same body                 -> {"predictions": [...]}
  POST /feedback same body + "labels" (opt "weights"/"offsets"/
                  "event_ids")             -> 202 intake accounting
                                              (--enable-updates only: the
                                              online tier re-solves the
                                              touched entities' random
                                              effects and publishes
                                              row-level delta swaps)
  GET  /metrics                            -> Prometheus text exposition
                                              (0.0.4; scrape this —
                                              includes serve.model_age_s
                                              and online.* instruments)
  GET  /metrics.json                       -> ServingMetrics JSON snapshot
  POST /swap     {"model_dir": "..."}      -> zero-downtime hot swap
  POST /rollback                           -> delta-aware: pending delta
                                              swaps revert to exact
                                              pre-delta rows, else the
                                              previous full model
  GET  /healthz                            -> status + version vector +
                                              updater vitals (thread
                                              liveness, last-cycle age,
                                              frozen entities) + the
                                              per-gate health verdict;
                                              HTTP 503 when a health gate
                                              is tripped (status
                                              "degraded")

  429 = Overloaded (queue full; POST /feedback rejections carry a
  Retry-After header derived from the online updater's observed drain
  rate), 504 = DeadlineExceeded, 400 = bad request.
  SIGUSR1 dumps a metrics snapshot to stderr; --metrics-interval dumps one
  periodically.

Graceful drain: SIGTERM/SIGINT stops accepting new requests, finishes the
in-flight micro-batches, flushes the FeedbackBuffer through the online
updater (when updates are enabled), closes everything cleanly, prints a
final {"drained": true, ...} line and exits 0.  A second signal aborts
immediately (utils.faults.GracefulPreemption semantics).

Fleet modes (photon_ml_tpu/fleet/ — see COMPONENTS.md "Replicated
serving"):

  --replica --replication-log DIR --replica-state DIR
      run as a fleet replica: join (snapshot bootstrap + log-tail replay
      + delta-program warmup), then keep converged with the publisher's
      model state by tailing the replication log.  /healthz returns 503
      until ready (and while draining/failed), so a front or Kubernetes
      probe holds traffic.  Followers refuse /swap, /rollback and
      /feedback (model state enters the fleet through the log only).
      Extra endpoints: GET /fleet/audit (version vector + per-table
      sha256 — the bit-identical convergence check), POST /fleet/drain.
  --replica --publish [--enable-updates]
      the PUBLISHER replica: every registry mutation (swap, delta,
      rollback) is appended to the replication log in mutation order;
      the online updater's delta stream replicates live.
  --front --replica-url URL [--replica-url URL ...]
      model-free routing front: /score + /predict round-robin over READY
      replicas (health-probed, failover, hedged tail latency, bounded
      in-flight -> 429), /feedback//swap//rollback proxied to the
      publisher replica, GET /fleet/audit fans out to every replica,
      POST /fleet/drain {"replica": URL} drains one replica out of
      rotation.
  --replica --shard K/N   (entity-sharded serving — COMPONENTS.md
      "Entity-sharded serving")
      this replica holds ONLY shard K of an N-way deterministic
      partition of the random-effect entity space (FE/MF replicate in
      full; replicated deltas filter to owned rows; tiered-store
      residency is sized to the slice).  POST /margins serves one
      fan-out leg; the publisher declares the partition with
      --shard-count N (a shard_map record anchors the log), and a front
      over sharded replicas fans /score //predict out per shard and
      re-folds bit-identically, degrading per --degraded-policy when a
      shard has no healthy replica.  GET /fleet/audit?shard=K on the
      publisher returns the full model filtered to shard K — equal
      hashes to a converged shard-K replica's own audit.

Fleet observability (COMPONENTS.md "Fleet observability"): --trace-out /
--run-log arm the span tracer in EVERY mode (front/replica/publish
included) with export on exit — including the SIGTERM drain path — at
cli.train parity; per-process run logs merge into one fleet timeline via
`python -m photon_ml_tpu.cli.trace merge`.  Requests propagate
X-Photon-Trace / X-Photon-Parent headers end to end (front routing →
replica scoring, /feedback → update cycle → replication record → replica
apply).  The flight recorder is always armed (bounded in-memory ring);
--flight-dir makes its dump-on-anomaly bundles durable, and POST
/flight/dump triggers a correlated dump (the front broadcasts it when a
replica leaves rotation).  A front's GET /metrics is the FEDERATED
exposition (own registry + every replica's with instance labels +
per-replica lag); GET /metrics/front is the front-only page.

Burst mode (--burst DATA.npz) — drive a synthetic client burst from a
GameDataset through the full micro-batching pipeline in-process, print the
metrics snapshot as the last stdout line, and exit; --output writes the
scores npz (row order preserved) so results can be diffed against
cli.score on the same data.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-serve")
    p.add_argument("--model-dir", default=None,
                   help="GAME model directory (any layout models/io.py "
                        "reads); required except in --front mode")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="HTTP port (0 = ephemeral; the bound port is "
                        "printed in the startup line)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="micro-batch coalescing window")
    p.add_argument("--max-batch", type=int, default=1024,
                   help="max rows per device call (power-of-two rounded)")
    p.add_argument("--max-queue", type=int, default=4096,
                   help="pending requests before shedding (Overloaded)")
    p.add_argument("--min-bucket", type=int, default=8,
                   help="smallest padded batch bucket")
    p.add_argument("--default-timeout-ms", type=float, default=None,
                   help="per-request deadline when the client sets none")
    p.add_argument("--metrics-interval", type=float, default=0.0,
                   help="seconds between periodic metrics dumps to stderr "
                        "(0 = only on SIGUSR1)")
    p.add_argument("--enable-updates", action="store_true",
                   help="online learning tier: accept POST /feedback and "
                        "publish per-entity random-effect delta swaps "
                        "into the live scorer")
    p.add_argument("--update-interval-ms", type=float, default=20.0,
                   help="idle poll period of the online update loop")
    p.add_argument("--update-micro-batch", type=int, default=16,
                   help="entity lanes per anchored online solve "
                        "(power-of-two rounded)")
    p.add_argument("--update-anchor-weight", type=float, default=1.0,
                   help="prior-pull strength toward the batch solution "
                        "(lambda of ||c - c0||^2)")
    p.add_argument("--update-max-rows-per-entity", type=int, default=64,
                   help="per-entity sample ceiling per online solve "
                        "(newest rows win)")
    p.add_argument("--feedback-max-pending", type=int, default=8192,
                   help="pending feedback rows before backpressure "
                        "(Overloaded / HTTP 429)")
    p.add_argument("--health-config", default=None, metavar="JSON",
                   help="arm the model-health monitor: HealthConfig as "
                        "inline JSON or @file ('{}' = defaults). Streaming "
                        "calibration + drift gates flip /healthz to "
                        "degraded, pause the online updater, and per "
                        "rollback_on trigger the delta-aware rollback")
    p.add_argument("--max-delta-log", type=int, default=4096,
                   help="delta undo-log bound; overflow drops the oldest "
                        "records LOUDLY and rollback degrades to a "
                        "full-model swap (serve.rollback_degraded)")
    # -- tiered entity store ------------------------------------------------
    p.add_argument("--store-budget-rows", type=int, default=None,
                   metavar="N",
                   help="serve random-effect tables through the tiered "
                        "entity store with a device hot set of N rows "
                        "(misses promote from the host warm tier / disk "
                        "cold tier; requires --store-dir)")
    p.add_argument("--store-dir", default=None, metavar="DIR",
                   help="cold-tier directory for --store-budget-rows "
                        "(sealed sha256-verified row segments; each "
                        "installed version gets a subdirectory)")
    p.add_argument("--store-warm-segments", type=int, default=64,
                   help="host warm-tier budget in segments "
                        "(x --store-seg-rows rows)")
    p.add_argument("--store-seg-rows", type=int, default=16384,
                   help="rows per cold segment file")
    # -- fleet: replica mode ------------------------------------------------
    p.add_argument("--replica", action="store_true",
                   help="run as a fleet replica: join from the "
                        "replication log, stay converged, 503 until "
                        "ready (requires --replication-log and "
                        "--replica-state)")
    p.add_argument("--publish", action="store_true",
                   help="this replica is the PUBLISHER: its registry "
                        "mutations (swaps, deltas, rollbacks) append to "
                        "the replication log in mutation order")
    p.add_argument("--replication-log", default=None, metavar="DIR",
                   help="replication log directory (shared filesystem "
                        "between publisher and replicas)")
    p.add_argument("--replica-state", default=None, metavar="DIR",
                   help="this replica's durable state dir (applied.json "
                        "— the crash/catch-up resume point)")
    p.add_argument("--replica-poll-ms", type=float, default=50.0,
                   help="log tail poll period of the replica apply loop")
    # -- fleet: entity sharding (fleet/shards.py) ---------------------------
    p.add_argument("--shard", default=None, metavar="K/N",
                   help="entity-sharded replica: hold only shard K of an "
                        "N-way partition of the random-effect entity "
                        "space (K in [0,N); fixed-effect/MF coordinates "
                        "replicate in full; replicated deltas filter to "
                        "owned rows; /margins serves fan-out legs)")
    p.add_argument("--shard-count", type=int, default=None, metavar="N",
                   help="publisher: declare the fleet's N-way entity "
                        "partition — anchors a shard_map record on the "
                        "replication log so joining replicas validate "
                        "their --shard against it (the publisher itself "
                        "stays unsharded)")
    p.add_argument("--shard-salt", default="photon",
                   help="shard-map hash salt (must match fleet-wide)")
    p.add_argument("--shard-spec-version", type=int, default=1,
                   help="shard-map version; a rebalance rolls out by "
                        "bumping it fleet-wide (the front adopts the "
                        "highest version it probes)")
    # -- fleet: front mode --------------------------------------------------
    p.add_argument("--front", action="store_true",
                   help="run the model-free routing front over "
                        "--replica-url replicas")
    p.add_argument("--replica-url", action="append", default=[],
                   help="replica base URL (repeatable); the first is the "
                        "publisher unless --publisher-url is given")
    p.add_argument("--publisher-url", default=None,
                   help="which replica accepts /feedback,/swap,/rollback")
    p.add_argument("--probe-interval-ms", type=float, default=250.0,
                   help="front: /healthz probe period per replica")
    p.add_argument("--hedge-ms", type=float, default=250.0,
                   help="front: hedge a duplicate request after this "
                        "long pending")
    p.add_argument("--front-timeout-ms", type=float, default=10_000.0,
                   help="front: per-attempt request timeout")
    p.add_argument("--max-inflight", type=int, default=256,
                   help="front: concurrently routed requests before "
                        "shedding (429)")
    p.add_argument("--degraded-policy", choices=("partial", "error"),
                   default="partial",
                   help="front, sharded fleets: what scoring gets when a "
                        "touched shard has no healthy replica — "
                        "'partial' folds the lost contributions as 0.0 "
                        "and stamps the response degraded, 'error' "
                        "fails those requests 503")
    # -- fleet observability (telemetry/distributed + telemetry/flight) -----
    p.add_argument("--trace-out", default=None, metavar="TRACE.json",
                   help="arm the telemetry span tracer and write a Chrome-"
                        "trace timeline at exit (cli.train parity; works "
                        "in every mode including --front/--replica/"
                        "--publish, and on the SIGTERM drain path)")
    p.add_argument("--run-log", default=None, metavar="RUN.jsonl",
                   help="stream span/event records as JSONL while "
                        "serving; arms the tracer like --trace-out.  The "
                        "per-process run logs are what `python -m "
                        "photon_ml_tpu.cli.trace merge` stitches into one "
                        "fleet timeline")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="durable flight-recorder bundle directory: the "
                        "always-on ring of recent spans/events/log lines "
                        "dumps here on health-gate trips, replica "
                        "failures, rollbacks, SIGTERM drain and crashes "
                        "(without it the ring stays in memory only)")
    p.add_argument("--flight-ring", type=int, default=4096,
                   help="flight-recorder ring capacity (records)")
    p.add_argument("--event-listener", action="append", default=[],
                   help="dotted EventListener class path (repeatable); "
                        "receives ScoringBatchEvent/ModelSwapEvent")
    p.add_argument("--burst", default=None, metavar="DATA",
                   help="burst mode: npz GameDataset to score as a "
                        "concurrent request stream, then exit")
    p.add_argument("--request-rows", type=int, default=1,
                   help="burst mode: rows per client request")
    p.add_argument("--threads", type=int, default=8,
                   help="burst mode: concurrent client threads")
    p.add_argument("--output", default=None,
                   help="burst mode: write scores npz (canonical row order)")
    return p


def _build_service(args):
    from photon_ml_tpu.serving import ScoringService, ServingConfig
    from photon_ml_tpu.utils.events import EventEmitter
    emitter = None
    if args.event_listener:
        emitter = EventEmitter()
        for dotted in args.event_listener:
            emitter.register_listener_class(dotted)
    shard_index = shard_count = None
    if getattr(args, "shard", None):
        shard_index, shard_count = _parse_shard(args.shard)
    cfg = ServingConfig(
        max_wait_s=args.max_wait_ms / 1e3,
        max_batch=args.max_batch,
        max_queue=args.max_queue,
        min_bucket=args.min_bucket,
        default_timeout_s=(None if args.default_timeout_ms is None
                           else args.default_timeout_ms / 1e3),
        max_delta_log=args.max_delta_log,
        store_budget_rows=args.store_budget_rows,
        store_dir=args.store_dir,
        store_warm_segments=args.store_warm_segments,
        store_seg_rows=args.store_seg_rows,
        shard_index=shard_index,
        shard_count=shard_count,
        shard_salt=getattr(args, "shard_salt", "photon"),
        shard_version=getattr(args, "shard_spec_version", 1))
    updates = None
    if args.enable_updates:
        from photon_ml_tpu.online import OnlineUpdateConfig
        updates = OnlineUpdateConfig(
            micro_batch=args.update_micro_batch,
            max_rows_per_entity=args.update_max_rows_per_entity,
            anchor_weight=args.update_anchor_weight,
            interval_s=args.update_interval_ms / 1e3,
            max_pending_rows=args.feedback_max_pending)
    health = None
    if args.health_config is not None:
        from photon_ml_tpu.cli.train import _load_json_arg
        from photon_ml_tpu.health import HealthConfig
        health = HealthConfig.from_dict(_load_json_arg(args.health_config))
    # publisher mode starts the updater only AFTER the replication
    # publish hook is attached (main wires that), so no delta can ever
    # land unreplicated
    start_updater = not (args.replica and args.publish)
    return ScoringService(model_dir=args.model_dir, config=cfg,
                          emitter=emitter, updates=updates, health=health,
                          start_updater=start_updater)


def _parse_shard(text: str):
    """--shard "K/N" -> (index, count)."""
    try:
        k, _, n = text.partition("/")
        index, count = int(k), int(n)
    except ValueError:
        raise SystemExit(f"--shard expects K/N (e.g. 0/4), got {text!r}")
    if not 0 <= index < count:
        raise SystemExit(f"--shard index {index} out of range for "
                         f"{count} shards")
    return index, count


def _dump_metrics(service, stream=sys.stderr):
    print(json.dumps(service.metrics_snapshot()), file=stream, flush=True)


def _arm_observability(args, proc: str) -> None:
    """cli.train wiring parity for the serve CLI, every mode: --trace-out
    / --run-log arm the span tracer (run logs are what `cli.trace merge`
    stitches); the flight recorder is ALWAYS armed — the ring stays in
    memory until --flight-dir makes its dumps durable."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import flight
    if args.trace_out or args.run_log or args.flight_dir:
        telemetry.install(run_log=args.run_log, proc=proc)
    flight.install(dump_dir=args.flight_dir, proc=proc,
                   ring_records=args.flight_ring)


def _export_observability(args) -> None:
    """Finish the tracer and export the Chrome trace — reached on clean
    exit, SIGTERM drain, AND crash paths (the finally in main)."""
    from photon_ml_tpu import telemetry
    telemetry.shutdown()
    if args.trace_out and telemetry.last_tracer() is not None:
        try:
            info = telemetry.write_chrome_trace(args.trace_out)
            print(f"trace written to {args.trace_out} "
                  f"({info['events']} events) — open at "
                  "https://ui.perfetto.dev", file=sys.stderr)
        except Exception as e:
            print(f"trace export failed: {e}", file=sys.stderr)


def _install_metrics_hooks(service, interval_s: float):
    try:  # SIGUSR1 works only on the main thread of the main interpreter
        signal.signal(signal.SIGUSR1, lambda *_: _dump_metrics(service))
    except (ValueError, AttributeError, OSError):
        pass
    if interval_s > 0:
        def loop():
            while True:
                time.sleep(interval_s)
                _dump_metrics(service)
        threading.Thread(target=loop, daemon=True,
                         name="photon-serving-metrics").start()


# -- burst mode ------------------------------------------------------------

def run_burst(service, data_path: str, request_rows: int, threads: int,
              output: str = None) -> dict:
    """Concurrent client burst over a GameDataset: split rows into
    `request_rows`-sized requests, fire them from a thread pool through the
    micro-batcher, reassemble scores in canonical row order."""
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from photon_ml_tpu.data.game_data import load_game_dataset
    ds = load_game_dataset(data_path)
    scorer = service.registry.scorer
    n = ds.num_rows
    chunks = [np.arange(lo, min(lo + request_rows, n))
              for lo in range(0, n, request_rows)]
    scores = np.empty(n, np.float64)
    errors = []

    def one(rows):
        feats, ids = scorer.requests_from_dataset(ds, rows)
        try:
            scores[rows] = service.score(feats, ids)
        except Exception as e:  # count, keep the burst going
            errors.append(f"{type(e).__name__}: {e}")

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        list(pool.map(one, chunks))
    wall = time.perf_counter() - t0
    if output and not errors:
        np.savez_compressed(output if output.endswith(".npz")
                            else output + ".npz", scores=scores)
    snap = service.metrics_snapshot()
    return {
        "mode": "burst", "rows": n, "requests": len(chunks),
        "threads": threads, "wall_s": round(wall, 4),
        "requests_per_sec": round(len(chunks) / wall, 1),
        "rows_per_sec": round(n / wall, 1),
        "failed_requests": len(errors),
        "first_errors": errors[:3],
        "output": output,
        "metrics": snap,
    }


# -- HTTP mode -------------------------------------------------------------

def _make_http_server(service, host: str, port: int, replica=None,
                      publisher=None):
    """`replica` (fleet.Replica) and `publisher` (fleet.FleetPublisher)
    extend the handler with the fleet endpoints and gate the model-state
    routes: followers refuse /swap, /rollback and /feedback — replicated
    model state enters through the log, never through a follower."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import numpy as np

    from photon_ml_tpu.fleet.replog import encode_array
    from photon_ml_tpu.serving import DeadlineExceeded, Overloaded
    from photon_ml_tpu.telemetry import distributed, flight

    follower = replica is not None and publisher is None

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):  # requests are metered, not logged
            pass

        def _reply(self, code: int, payload: dict, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def _reply_text(self, code: int, body: str, content_type: str):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/metrics":
                # Prometheus scrape endpoint (text exposition 0.0.4); the
                # JSON snapshot moved to /metrics.json
                self._reply_text(
                    200, service.prometheus_metrics(),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/metrics.json":
                self._reply(200, service.metrics_snapshot())
            elif self.path == "/healthz":
                payload = service.healthz()
                # every probe is also a clock probe: the front estimates
                # this process's wall-clock offset from (pid, wall_s),
                # which is what aligns the merged fleet timeline
                payload["telemetry"] = distributed.clock_info()
                if publisher is not None:
                    fleet = publisher.status()
                    # the publisher IS the source of truth: its applied
                    # seq is the log head (what replica lag measures
                    # against)
                    head = publisher.head_seq()
                    fleet.update({"ready": fleet["failed"] is None,
                                  "applied_seq": head, "head_seq": head,
                                  "lag_seq": 0})
                    payload["fleet"] = fleet
                    if fleet["failed"] is not None:
                        payload["status"] = "degraded"
                elif replica is not None:
                    # joining / draining / failed -> 503 so the front
                    # (or a stock Kubernetes probe) holds traffic until
                    # the replica is converged and warm
                    payload["fleet"] = replica.status()
                    if not replica.healthy():
                        payload["status"] = "degraded"
                # degraded -> 503 so a stock load balancer / Kubernetes
                # probe takes the replica out without parsing the body
                self._reply(200 if payload["status"] == "ok" else 503,
                            payload)
            elif self.path.split("?", 1)[0] == "/fleet/audit":
                from urllib.parse import parse_qs, urlsplit
                q = parse_qs(urlsplit(self.path).query)
                if q.get("shard") and publisher is not None:
                    # the publisher-side half of a per-shard audit: its
                    # FULL tables filtered to shard K's owned rows — a
                    # converged shard-K replica's plain audit reports
                    # the identical sha256 hashes
                    try:
                        self._reply(200, publisher.shard_audit(
                            int(q["shard"][0])))
                    except ValueError as e:
                        self._reply(400, {"error": str(e)})
                elif replica is not None:
                    self._reply(200, replica.audit())
                else:
                    audit = service.audit()
                    if publisher is not None:
                        audit.update({"role": "publisher",
                                      "applied_seq": publisher.head_seq()})
                    self._reply(200, audit)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            try:
                req = self._body()
            except ValueError as e:
                return self._reply(400, {"error": f"bad JSON: {e}"})
            try:
                if self.path in ("/score", "/predict"):
                    # the server half of the propagated hop: adopts the
                    # front's X-Photon-Trace/-Parent headers (minting an
                    # id for direct traffic), so this request's spans
                    # join the fleet-wide tree at merge time
                    with distributed.server_span("serve_request",
                                                 self.headers,
                                                 path=self.path):
                        feats = {s: np.asarray(v, np.float64)
                                 for s, v in (req.get("features")
                                              or {}).items()}
                        ids = {t: np.asarray(v, dtype=object)
                               for t, v in (req.get("ids") or {}).items()}
                        timeout = req.get("timeout_ms")
                        timeout = (None if timeout is None
                                   else timeout / 1e3)
                        if self.path == "/score":
                            out = service.score(feats, ids,
                                                timeout=timeout)
                            key = "scores"
                        else:
                            out = service.predict(feats, ids,
                                                  timeout=timeout)
                            key = "predictions"
                    self._reply(200, {key: np.asarray(out).tolist(),
                                      "model_version": service.model_version})
                elif self.path == "/margins":
                    # one leg of an entity-sharded fan-out (fronts call
                    # this; fleet/shards.merge_margins re-folds the
                    # legs).  Margins travel as encode_array payloads —
                    # exact dtype + bytes, since the merge's bit-identity
                    # depends on folding the device compute dtype, not a
                    # JSON float round-trip
                    with distributed.server_span("serve_request",
                                                 self.headers,
                                                 path=self.path):
                        feats = {s: np.asarray(v, np.float64)
                                 for s, v in (req.get("features")
                                              or {}).items()}
                        ids = {t: np.asarray(v, dtype=object)
                               for t, v in (req.get("ids") or {}).items()}
                        out = service.score_margins(feats, ids)
                    out["margins"] = {name: encode_array(m)
                                      for name, m in out["margins"].items()}
                    self._reply(200, out)
                elif self.path == "/feedback":
                    if follower:
                        return self._reply(403, {
                            "error": "this is a follower replica: "
                                     "feedback goes to the publisher "
                                     "(model state enters the fleet "
                                     "through the replication log)"})
                    if service.updater is None:
                        return self._reply(400, {
                            "error": "online updates are not enabled "
                                     "(start with --enable-updates)"})
                    feats = {s: np.asarray(v, np.float64)
                             for s, v in (req.get("features") or {}).items()}
                    ids = {t: np.asarray(v, dtype=object)
                           for t, v in (req.get("ids") or {}).items()}
                    if req.get("labels") is None:
                        return self._reply(400, {"error": "labels required"})
                    # the span scope is what stamps the request id onto
                    # the buffered observations (updater.submit reads the
                    # thread-local context), carrying it into the delta's
                    # replication trace
                    with distributed.server_span("serve_request",
                                                 self.headers,
                                                 path=self.path):
                        out = service.feedback(
                            feats, ids,
                            np.asarray(req["labels"], np.float64),
                            weights=req.get("weights"),
                            offsets=req.get("offsets"),
                            event_ids=req.get("event_ids"))
                    out["version_vector"] = service.version_vector()
                    self._reply(202, out)
                elif self.path == "/flight/dump":
                    # the front's fleet-wide postmortem fan-out (or an
                    # operator asking for the window by hand)
                    bundle = flight.trigger(
                        req.get("reason") or "replica.unhealthy",  # photonlint: disable=PH008 -- forwards the broadcaster's already-validated reason (trigger() re-validates at runtime)
                        trigger_id=req.get("trigger_id"),
                        **{k: str(v)
                           for k, v in (req.get("attrs") or {}).items()})
                    self._reply(200, {"bundle": bundle,
                                      "armed": flight.armed()})
                elif self.path == "/swap":
                    if follower:
                        return self._reply(403, {
                            "error": "this is a follower replica: swap "
                                     "on the publisher (it replicates "
                                     "through the log)"})
                    if not req.get("model_dir"):
                        return self._reply(400,
                                           {"error": "model_dir required"})
                    v = service.swap(req["model_dir"], req.get("version"))
                    self._reply(200, {"version": v})
                elif self.path == "/rollback":
                    if follower:
                        return self._reply(403, {
                            "error": "this is a follower replica: roll "
                                     "back on the publisher (it "
                                     "replicates through the log)"})
                    self._reply(200, {"version": service.rollback()})
                elif self.path == "/fleet/drain" and replica is not None:
                    self._reply(200, replica.drain())
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})
            except Overloaded as e:
                headers = None
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    # integer delta-seconds per RFC 9110; derived from
                    # the updater's observed feedback drain rate
                    headers = {"Retry-After":
                               str(max(1, int(round(retry_after))))}
                    self._reply(429, {"error": str(e),
                                      "retry_after_s":
                                          round(retry_after, 3)},
                                headers)
                else:
                    self._reply(429, {"error": str(e)})
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e)})
            except (ValueError, KeyError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return ThreadingHTTPServer((host, port), Handler)


def _make_front_server(front, host: str, port: int):
    """The routing front's HTTP server: /score + /predict fan out over
    ready replicas, model-state routes proxy to the publisher, fleet
    introspection aggregates the replicas."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from photon_ml_tpu.fleet import NoReadyReplica
    from photon_ml_tpu.serving import Overloaded
    from photon_ml_tpu.telemetry import distributed, flight

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):
            pass

        def _reply(self, code: int, payload: dict, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code: int, body: str, content_type: str):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def do_GET(self):
            if self.path == "/metrics":
                # the FEDERATED exposition: the front's own registry plus
                # every reachable replica's, per-instance labels, plus
                # the probe-derived per-replica replication lag
                self._reply_text(
                    200, front.federated_prometheus(),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/metrics/front":
                # the front's own registry alone (the parity-contract
                # surface; scrape this to exclude replica fan-out cost)
                self._reply_text(
                    200, front.prometheus_metrics(),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/metrics.json":
                self._reply(200, front.federated_snapshot())
            elif self.path == "/healthz":
                status = front.status()
                # sharded fleets: the front is healthy only while EVERY
                # shard has a healthy replica — a dark shard means part
                # of the entity space cannot be scored exactly, and a
                # stock load balancer should see that without parsing
                shards_down = (status.get("shards") or {}).get(
                    "shards_down") or []
                ok = status["ready_replicas"] > 0 and not shards_down
                status["status"] = "ok" if ok else "degraded"
                status["telemetry"] = distributed.clock_info()
                self._reply(200 if ok else 503, status)
            elif self.path == "/fleet/audit":
                self._reply(200, front.audit())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            try:
                req = self._body()
            except ValueError as e:
                return self._reply(400, {"error": f"bad JSON: {e}"})
            try:
                if self.path in ("/score", "/predict"):
                    timeout = req.get("timeout_ms")
                    timeout = None if timeout is None else timeout / 1e3
                    # adopt the client's trace context (if any) so
                    # front.route()'s span carries the caller's id
                    distributed.set_context(
                        self.headers.get(distributed.TRACE_HEADER),
                        self.headers.get(distributed.PARENT_HEADER))
                    try:
                        status, payload = front.route(self.path, req,
                                                      timeout=timeout)
                    finally:
                        distributed.set_context(None, None)
                    self._reply(status, payload)
                elif self.path in ("/feedback", "/swap", "/rollback"):
                    distributed.set_context(
                        self.headers.get(distributed.TRACE_HEADER),
                        self.headers.get(distributed.PARENT_HEADER))
                    try:
                        status, payload, headers = front.route_publisher(
                            "POST", self.path, req)
                    finally:
                        distributed.set_context(None, None)
                    self._reply(status, payload, headers)
                elif self.path == "/flight/dump":
                    bundle = flight.trigger(
                        req.get("reason") or "replica.unhealthy",  # photonlint: disable=PH008 -- forwards the broadcaster's already-validated reason (trigger() re-validates at runtime)
                        trigger_id=req.get("trigger_id"),
                        **{k: str(v)
                           for k, v in (req.get("attrs") or {}).items()})
                    self._reply(200, {"bundle": bundle,
                                      "armed": flight.armed()})
                elif self.path == "/fleet/drain":
                    if not req.get("replica"):
                        return self._reply(
                            400, {"error": "replica URL required"})
                    self._reply(200, front.drain(req["replica"]))
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})
            except Overloaded as e:
                self._reply(429, {"error": str(e)})
            except NoReadyReplica as e:
                self._reply(503, {"error": str(e)})
            except ValueError as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return ThreadingHTTPServer((host, port), Handler)


def _serve_with_graceful_drain(httpd, poll_interval: float = 0.1):
    """Run the HTTP loop until SIGTERM/SIGINT requests a graceful drain
    (or the server dies).  Returns (drained, aborted): on drain the
    server has STOPPED ACCEPTING and in-flight handlers have finished; a
    second signal aborts immediately (aborted=True — skip the flush)."""
    from photon_ml_tpu.utils import faults

    worker = threading.Thread(target=httpd.serve_forever,
                              kwargs={"poll_interval": poll_interval},
                              daemon=True, name="photon-serve-http")
    drained = aborted = False
    with faults.GracefulPreemption():
        worker.start()
        try:
            while worker.is_alive():
                if faults.preemption_requested():
                    drained = True
                    break
                time.sleep(poll_interval)
        except KeyboardInterrupt:  # second signal: the operator means it
            drained, aborted = True, True
    # stop accepting; ThreadingHTTPServer.shutdown returns after the
    # serve loop exits, and in-flight handler threads complete their
    # responses before the process moves on to flushing state
    httpd.shutdown()
    worker.join(timeout=10.0)
    return drained, aborted


def _run_front(args) -> int:
    from photon_ml_tpu.fleet import Front, FrontConfig
    from photon_ml_tpu.telemetry import flight
    front = Front(
        args.replica_url, publisher_url=args.publisher_url,
        config=FrontConfig(
            probe_interval_s=args.probe_interval_ms / 1e3,
            hedge_after_s=args.hedge_ms / 1e3,
            request_timeout_s=args.front_timeout_ms / 1e3,
            max_inflight=args.max_inflight,
            degraded_policy=args.degraded_policy))
    front.probe_once()  # populate readiness before the first request
    httpd = _make_front_server(front, args.host, args.port)
    print(json.dumps({
        "serving": f"http://{args.host}:{httpd.server_address[1]}",
        "mode": "front",
        "replicas": args.replica_url,
        "publisher": args.publisher_url or args.replica_url[0],
        "degraded_policy": args.degraded_policy,
        "endpoints": ["/score", "/predict", "/feedback", "/metrics",
                      "/metrics/front", "/metrics.json", "/swap",
                      "/rollback", "/healthz", "/fleet/audit",
                      "/fleet/drain", "/flight/dump"],
    }), flush=True)
    try:
        drained, aborted = _serve_with_graceful_drain(httpd)
    finally:
        httpd.server_close()
        front.close()
    if drained:
        flight.trigger("serve.drain", mode="front", aborted=aborted)
        print(json.dumps({"drained": True, "aborted": aborted,
                          "mode": "front"}), flush=True)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.front:
        if not args.replica_url:
            raise SystemExit("--front requires at least one --replica-url")
    else:
        if not args.model_dir:
            raise SystemExit(
                "--model-dir is required (except in --front mode)")
        if args.replica and not (args.replication_log
                                 and args.replica_state):
            raise SystemExit("--replica requires --replication-log and "
                             "--replica-state")
        if args.enable_updates and args.replica and not args.publish:
            raise SystemExit("a follower replica cannot run the online "
                             "updater (--enable-updates needs --publish): "
                             "model state enters the fleet through the "
                             "replication log")
        if args.shard and args.publish:
            raise SystemExit("the publisher stays unsharded (it holds "
                             "the full model); declare the fleet's "
                             "partition with --shard-count instead")
        if args.shard and args.enable_updates:
            raise SystemExit("a sharded replica cannot run the online "
                             "updater: deltas are solved on the "
                             "publisher and replicate shard-filtered")
        if args.shard_count is not None and not args.publish:
            raise SystemExit("--shard-count is the publisher's flag "
                             "(--replica --publish); shard replicas "
                             "take --shard K/N")
    _arm_observability(args, proc_label(args))
    from photon_ml_tpu.telemetry import flight
    try:
        if args.front:
            return _run_front(args)
        return _run_serve(args)
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as e:
        # the process is dying on an unhandled error: the ring holds the
        # window that led here — get it on disk before the stack unwinds
        flight.trigger("serve.crash", error=f"{type(e).__name__}: {e}")
        raise
    finally:
        _export_observability(args)


def _run_serve(args) -> int:
    from photon_ml_tpu.telemetry import flight
    from photon_ml_tpu.utils.jax_cache import enable_persistent_cache
    enable_persistent_cache()
    t0 = time.perf_counter()
    service = _build_service(args)
    load_s = time.perf_counter() - t0
    if args.burst:
        try:
            result = run_burst(service, args.burst, args.request_rows,
                               args.threads, args.output)
        finally:
            service.close()
        result["model_load_s"] = round(load_s, 3)
        print(json.dumps(result))
        return 1 if result["failed_requests"] else 0

    replica = publisher = None
    join_info = None
    if args.replica:
        from photon_ml_tpu.fleet import (FleetPublisher, Replica,
                                         ReplicaConfig, ReplicationLog)
        log = ReplicationLog(args.replication_log)
        if args.publish:
            shard_spec = None
            if args.shard_count is not None:
                from photon_ml_tpu.fleet import ShardSpec
                shard_spec = ShardSpec(num_shards=args.shard_count,
                                       salt=args.shard_salt,
                                       version=args.shard_spec_version)
            publisher = FleetPublisher(service, log,
                                       model_dir=args.model_dir,
                                       shard_spec=shard_spec)
            if service.updater is not None:
                # started HERE, after the publish hook attached: no delta
                # may ever land unreplicated
                service.updater.start()
        else:
            replica = Replica(
                service, log, args.replica_state,
                ReplicaConfig(poll_interval_s=args.replica_poll_ms / 1e3))
            join_info = replica.join()
            replica.start()

    httpd = _make_http_server(service, args.host, args.port,
                              replica=replica, publisher=publisher)
    _install_metrics_hooks(service, args.metrics_interval)
    print(json.dumps({
        "serving": f"http://{args.host}:{httpd.server_address[1]}",
        "mode": ("publisher" if publisher is not None else
                 "replica" if replica is not None else "standalone"),
        "model_dir": args.model_dir,
        "model_version": service.model_version,
        "model_load_s": round(load_s, 3),
        "buckets": service.registry.scorer.bucket_sizes(),
        "updates_enabled": service.updater is not None,
        "health_enabled": service.health is not None,
        "shard": service.registry.scorer.shard_info(),
        "shard_count_published": args.shard_count,
        "join": join_info,
        "endpoints": ["/score", "/predict", "/margins", "/feedback",
                      "/metrics", "/metrics.json", "/swap", "/rollback",
                      "/healthz", "/flight/dump"]
        + (["/fleet/audit", "/fleet/drain"] if args.replica else []),
    }), flush=True)
    try:
        drained, aborted = _serve_with_graceful_drain(httpd)
    finally:
        httpd.server_close()
    if drained:
        # dump the flight ring BEFORE the flush/close teardown mutates
        # state — the drain window is part of the postmortem trail
        flight.trigger("serve.drain", mode=proc_label(args),
                       aborted=aborted)
    flushed = None
    if drained and not aborted and service.updater is not None \
            and not service.updater.paused:
        # the drain contract: everything the intake admitted either
        # publishes (and replicates) or is accounted before exit
        flushed = service.updater.flush()
    if replica is not None:
        replica.close()
    service.close()
    _dump_metrics(service)
    if drained:
        print(json.dumps({
            "drained": True, "aborted": aborted,
            "feedback_flushed": flushed,
            "version_vector": service.version_vector()}), flush=True)
    return 0


def proc_label(args) -> str:
    return ("front" if args.front else
            "publisher" if args.replica and args.publish else
            "replica" if args.replica else "serve")


if __name__ == "__main__":
    raise SystemExit(main())
