"""Continuous-training CLI: replay the feedback lane into a refit cycle.

The operational entry point of photon_ml_tpu/refit/ — load the incumbent
model, compact the durable feedback lane into training chunks, run the
warm anchored refit, validate candidate vs incumbent on the log's
held-back tail, and (on a win) swap the candidate in:

  # manual one-shot
  python -m photon_ml_tpu.cli.refit --model-dir out/best \
      --feedback-log /srv/fb --chunks /srv/chunks --model-root /srv/models

  # cron-style: a cycle every 15 minutes until SIGINT
  ... --interval 900

  # automatic remediation: watch a serving fleet's /healthz and refit
  # after 3 consecutive degraded polls, at most every 10 minutes
  ... --on-trip --healthz-url http://front:8080/healthz \
      --trip-polls 3 --cooloff 600

With --replication-log the winning swap is appended to the fleet's
replication log (fleet.FleetPublisher), so every replica tailing it
picks the new model up exactly like any other publisher swap — rollback
and version-vector semantics intact.

SINGLE-WRITER CAVEAT: the feedback lane is opened with the replication
log's recovery discipline, which may truncate a torn tail.  Run this CLI
against a lane whose writer is stopped, a filesystem snapshot, or let
the serving process host the trigger in-process instead (refit.trigger).

Exit codes: 0 = cycle ran (swapped or not; see the printed JSON),
1 = the cycle failed (the incumbent keeps serving), 2 = bad arguments.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-refit")
    p.add_argument("--model-dir", required=True,
                   help="incumbent model directory (any models/io layout)")
    p.add_argument("--feedback-log", required=True, metavar="DIR",
                   help="the durable feedback lane (fleet.FeedbackLog; "
                        "cli.serve --feedback-log)")
    p.add_argument("--chunks", required=True, metavar="DIR",
                   help="compactor output directory (chunk files + "
                        "manifest.json; reused incrementally across runs)")
    p.add_argument("--model-root", required=True, metavar="DIR",
                   help="where candidate version directories are written")
    p.add_argument("--chunk-rows", type=int, default=1024,
                   help="rows per sealed chunk, power of two (default "
                        "%(default)s; part of the chunk store's identity)")
    p.add_argument("--holdout-frac", type=float, default=0.2,
                   help="newest fraction of the log held back for "
                        "validation (default %(default)s)")
    p.add_argument("--outer-iterations", type=int, default=2,
                   help="alternating FE/RE passes (default %(default)s)")
    p.add_argument("--fe-iterations", type=int, default=50)
    p.add_argument("--re-iterations", type=int, default=100)
    p.add_argument("--anchor-weight", type=float, default=1.0,
                   help="pull toward the incumbent's random-effect rows")
    p.add_argument("--min-improvement", type=float, default=0.0,
                   help="holdout-loss margin the candidate must win by")
    p.add_argument("--version", default=None,
                   help="explicit candidate version name (default: "
                        "refit-seq<checkpoint>-n<rows>)")
    p.add_argument("--replication-log", default=None, metavar="DIR",
                   help="append winning swaps to this fleet replication "
                        "log (fleet.FleetPublisher)")
    p.add_argument("--interval", type=float, default=None, metavar="S",
                   help="cron-style mode: run a cycle every S seconds "
                        "until interrupted")
    p.add_argument("--on-trip", action="store_true",
                   help="automatic mode: refit on a sustained degraded "
                        "/healthz verdict (needs --healthz-url)")
    p.add_argument("--healthz-url", default=None,
                   help="serving /healthz endpoint --on-trip watches")
    p.add_argument("--trip-polls", type=int, default=2,
                   help="consecutive degraded polls that fire a cycle")
    p.add_argument("--cooloff", type=float, default=60.0,
                   help="minimum seconds between automatic cycles")
    p.add_argument("--poll", type=float, default=2.0,
                   help="trigger poll period in automatic modes")
    return p


class _HealthzProbe:
    """A `degraded` property over a serving /healthz endpoint — the duck
    type (HealthMonitor.degraded) the RefitTrigger's on_trip mode polls.
    Unreachable endpoints read as healthy: a refit is the wrong remedy
    for a dead server."""

    def __init__(self, url: str, timeout_s: float = 2.0):
        self.url = url
        self.timeout_s = timeout_s

    @property
    def degraded(self) -> bool:
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(self.url,
                                        timeout=self.timeout_s) as resp:
                body = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 503:      # the serve CLI's degraded status code
                return True
            return False
        except (OSError, ValueError):
            return False
        health = body.get("health") or {}
        return (body.get("status") == "degraded"
                or health.get("status") == "degraded")


def _result_line(result) -> str:
    return json.dumps(dataclasses.asdict(result), sort_keys=True)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.on_trip and args.healthz_url is None:
        parser.error("--on-trip needs --healthz-url (the verdict source)")
    if args.on_trip and args.interval is not None:
        parser.error("pick one of --interval / --on-trip")

    from photon_ml_tpu.fleet.replog import FeedbackLog
    from photon_ml_tpu.refit import (CompactorConfig, LogCompactor,
                                     RefitConfig, RefitDriver, RefitTrigger,
                                     TriggerConfig)
    from photon_ml_tpu.serving import ScoringService

    service = ScoringService(model_dir=args.model_dir, start_updater=False)
    publisher = None
    if args.replication_log is not None:
        from photon_ml_tpu.fleet import ReplicationLog
        from photon_ml_tpu.fleet.replica import FleetPublisher
        publisher = FleetPublisher(service,
                                   ReplicationLog(args.replication_log),
                                   model_dir=args.model_dir)
    log = FeedbackLog(args.feedback_log)
    dropped = log.recover()
    if dropped:
        print(f"feedback lane: truncated {dropped} torn tail byte(s)",
              file=sys.stderr)
    compactor = LogCompactor(log, args.chunks,
                             CompactorConfig(chunk_rows=args.chunk_rows))
    log.register_consumer("refit-compactor", compactor.checkpoint_seq)
    driver = RefitDriver(
        service.registry, compactor, args.model_root,
        RefitConfig(holdout_frac=args.holdout_frac,
                    outer_iterations=args.outer_iterations,
                    fe_iterations=args.fe_iterations,
                    re_iterations=args.re_iterations,
                    anchor_weight=args.anchor_weight,
                    min_loss_improvement=args.min_improvement),
        metrics=service.metrics)

    trigger = None
    try:
        if args.interval is None and not args.on_trip:
            result = driver.run_once(version=args.version)
            print(_result_line(result))
            return 0
        if args.on_trip:
            cfg = TriggerConfig(mode="on_trip", poll_s=args.poll,
                                trip_polls=args.trip_polls,
                                cooloff_s=args.cooloff)
            trigger = RefitTrigger(driver, health=_HealthzProbe(
                args.healthz_url), config=cfg)
        else:
            cfg = TriggerConfig(mode="interval", interval_s=args.interval,
                                poll_s=args.poll)
            trigger = RefitTrigger(driver, config=cfg)
        while True:                       # SIGINT ends the watch loop
            result = trigger.poll()
            if result is not None:
                print(_result_line(result), flush=True)
            elif trigger.state()["last_error"]:
                print(json.dumps({"failed": trigger.state()["last_error"]}),
                      file=sys.stderr, flush=True)
            time.sleep(cfg.poll_s)
    except KeyboardInterrupt:
        state = trigger.state() if trigger is not None else {}
        print(json.dumps({"stopped": True, **state}), flush=True)
        return 0
    except Exception as e:
        print(f"refit failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    finally:
        del publisher          # hook-driven; no background state to stop
        service.close()


if __name__ == "__main__":
    sys.exit(main())
