"""Diagnostics CLI: trained model + data -> JSON/markdown quality report.

reference: the legacy Driver's DIAGNOSED stage (photon-client/.../
Driver.scala:468-607), which assembles metrics, Hosmer-Lemeshow, bootstrap,
feature importance, and fitting diagnostics into an HTML report.  Here the
same analyses emit report.json + report.md + a self-contained report.html
(inline CSS/SVG, no plotting stack).

  python -m photon_ml_tpu.cli.diagnose --model-dir out/best --data d.npz \
      --output-dir diag/ [--coordinate fixed] [--bootstrap-samples 10]
      [--skip-fitting] [--skip-bootstrap]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-diagnose")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--data", required=True,
                   help=".npz GameDataset, .libsvm, or Avro input (file, "
                        "directory, or glob; resolved in the MODEL's "
                        "feature/entity spaces like cli.score)")
    p.add_argument("--feature-shard-map", default=None,
                   help="Avro inputs: JSON (inline or @file) shard -> bags "
                        "merge map (see cli.train)")
    p.add_argument("--id-columns", default=None,
                   help="Avro inputs: comma-separated id tags to extract")
    p.add_argument("--input-columns", default=None,
                   help="Avro inputs: JSON remap of response/offset/weight/"
                        "uid column names (see cli.train)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--coordinate", default=None,
                   help="fixed-effect coordinate to analyze in depth "
                        "(default: the first fixed-effect coordinate)")
    p.add_argument("--bootstrap-samples", type=int, default=10)
    p.add_argument("--skip-bootstrap", action="store_true")
    p.add_argument("--skip-fitting", action="store_true")
    p.add_argument("--x64", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import jax
    if args.x64:
        jax.config.update("jax_enable_x64", True)

    from photon_ml_tpu.data.stats import BasicStatisticalSummary
    from photon_ml_tpu.diagnostics import (
        DiagnosticReport, bootstrap_training, evaluate_scores,
        feature_importance, fitting_diagnostic, hosmer_lemeshow,
        kendall_tau_analysis, render_html, render_markdown,
    )
    from photon_ml_tpu.game.config import FixedEffectCoordinateConfig
    from photon_ml_tpu.models.game import FixedEffectModel
    from photon_ml_tpu.models.io import load_game_model
    from photon_ml_tpu.ops import TASK_LOSSES

    model, config = load_game_model(args.model_dir)
    # Avro inputs resolve in the MODEL's feature/entity spaces (the scoring
    # loader pins index maps and errors when the model records none —
    # misaligned columns would silently corrupt every diagnostic)
    from photon_ml_tpu.cli.score import (_load_scoring_data,
                                         require_fully_labeled)
    ds, _uids = _load_scoring_data(args, model, args.model_dir)
    require_fully_labeled(ds, "diagnostics")
    task = model.task_type
    loss = TASK_LOSSES[task]

    # full-model metrics from the composite score (margins + offsets)
    import jax.numpy as jnp
    margins = np.asarray(model.score_dataset(ds), dtype=np.float64)
    if ds.offsets is not None:
        margins = margins + ds.offsets
    preds = np.asarray(loss.mean(jnp.asarray(margins)))

    # the in-depth single-GLM analyses run on a fixed-effect coordinate
    fe_name, fe_model = None, None
    for name, m in model.coordinates.items():
        if isinstance(m, FixedEffectModel) and (
                args.coordinate is None or name == args.coordinate):
            fe_name, fe_model = name, m
            break
    if args.coordinate is not None and fe_name != args.coordinate:
        raise SystemExit(f"no fixed-effect coordinate {args.coordinate!r}")

    coefs = (np.asarray(fe_model.glm.coefficients.means)
             if fe_model is not None else None)
    metrics = evaluate_scores(task, preds, margins, ds.response,
                              coefficients=coefs)
    report = DiagnosticReport(task_type=task, metrics=metrics)

    if fe_model is not None:
        x = ds.feature_shards[fe_model.feature_shard]
        summary = BasicStatisticalSummary.from_features(
            np.asarray(x), ds.weights)
        imap = ds.index_maps.get(fe_model.feature_shard)
        keys = imap.index_to_key if imap is not None else None
        report.feature_importance = feature_importance(
            coefs, summary, keys, "expected_magnitude")

        if task == "logistic_regression":
            report.hosmer_lemeshow = hosmer_lemeshow(preds, ds.response,
                                                     x.shape[1])
        report.independence = kendall_tau_analysis(preds, ds.response - preds)

        opt = None
        if config is not None and fe_name in config.coordinates:
            c = config.coordinates[fe_name]
            if isinstance(c, FixedEffectCoordinateConfig):
                opt = c.optimization
        kw = dict(
            optimizer_config=opt.optimizer if opt else None,
            regularization=opt.regularization if opt else None,
            regularization_weight=opt.regularization_weight if opt else 0.0)
        kw = {k: v for k, v in kw.items() if v is not None}
        if not args.skip_bootstrap:
            report.bootstrap = bootstrap_training(
                x, ds.response, task,
                num_bootstrap_samples=args.bootstrap_samples,
                weights=ds.weights, offsets=ds.offsets, **kw)
        if not args.skip_fitting:
            report.fitting = fitting_diagnostic(
                x, ds.response, task, weights=ds.weights, offsets=ds.offsets,
                **kw)

    os.makedirs(args.output_dir, exist_ok=True)
    with open(os.path.join(args.output_dir, "report.json"), "w") as f:
        f.write(report.to_json())
    with open(os.path.join(args.output_dir, "report.md"), "w") as f:
        f.write(render_markdown(report))
    with open(os.path.join(args.output_dir, "report.html"), "w") as f:
        f.write(render_html(report))
    print(json.dumps({"metrics": metrics,
                      "coordinate": fe_name,
                      "output": args.output_dir}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
