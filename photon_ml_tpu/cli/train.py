"""Training CLI: the single driver replacing both reference drivers.

reference: the legacy stage-machine Driver (photon-client/.../Driver.scala:71-739)
and the GAME training driver (photon-client/.../cli/game/training/Driver.scala:50-505)
are folded into one subcommand (SURVEY §7 "What NOT to port"):

  python -m photon_ml_tpu.cli.train \
      --train-data data.npz|data.libsvm --task logistic_regression \
      --output-dir out/ [--validation-data v.npz] [--config game.json]
      [--reg-weights 0.1,1,10] [--evaluators AUC,PRECISION@K:10:userId] ...

Without --config, a single fixed-effect coordinate over the "global" shard
is trained (the legacy single-GLM pipeline: preprocess -> train lambda sweep
-> validate -> select best); with --config (GameTrainingConfig JSON), the
full GAME coordinate-descent path runs.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu-train",
        description="Train GLM / GAME mixed-effect models on TPU (JAX)")
    p.add_argument("--train-data", required=True,
                   help=".npz GameDataset, .libsvm file, or Avro input "
                        "(.avro file, directory of .avro files, or glob)")
    p.add_argument("--validation-data", default=None)
    p.add_argument("--feature-shard-map", default=None,
                   help="Avro inputs: JSON (inline or @file) mapping shard "
                        "name -> list of feature-bag fields to merge, e.g. "
                        "'{\"global\": [\"features\"], \"per_user\": "
                        "[\"userFeatures\"]}' (reference: readMerged "
                        "featureColumnMap); default merges the 'features' "
                        "bag into one 'global' shard")
    p.add_argument("--index-map-dir", default=None,
                   help="directory of prebuilt per-shard index maps "
                        "(python -m photon_ml_tpu.cli.index); pins this "
                        "job's Avro ingest to that frozen feature space so "
                        "separate jobs share identical feature dimensions "
                        "and key->column assignment (reference: "
                        "FeatureIndexingJob + PalDBIndexMapLoader)")
    p.add_argument("--selected-features", default=None,
                   help="Avro file of FeatureAvro {name, term} records: "
                        "restrict training to exactly these features (+ "
                        "intercept), like the legacy driver's "
                        "selected-features file (reference: GLMSuite "
                        "selectedFeaturesFile).  Single-shard Avro input "
                        "only; exclusive with --index-map-dir")
    p.add_argument("--id-columns", default=None,
                   help="Avro inputs: comma-separated random-effect id tags "
                        "to extract (top-level field or metadataMap key)")
    p.add_argument("--input-columns", default=None,
                   help="Avro inputs: JSON remapping of input column names, "
                        "e.g. '{\"response\": \"label\", \"weight\": \"w\"}' "
                        "(reference: InputColumnsNames; keys: response, "
                        "offset, weight, uid)")
    p.add_argument("--input-date-range", default=None,
                   help="restrict date-partitioned input to "
                        "'yyyyMMdd-yyyyMMdd': reads "
                        "<train-data>/daily/YYYY/MM/DD per day (reference: "
                        "GameDriver.pathsForDateRange)")
    p.add_argument("--input-days-ago", default=None,
                   help="same as --input-date-range but as 'START-END' days "
                        "ago (e.g. '90-1'); mutually exclusive with it")
    p.add_argument("--validation-date-range", default=None,
                   help="date range for the VALIDATION input's daily/ tree "
                        "(each input resolves its own range, as in the "
                        "reference)")
    p.add_argument("--validation-days-ago", default=None,
                   help="days-ago range for the validation input")
    p.add_argument("--save-feature-stats", action="store_true",
                   help="persist per-shard BasicStatisticalSummary to "
                        "<output-dir>/feature-stats/<shard>.json (reference: "
                        "Driver.calculateAndSaveFeatureShardStats)")
    p.add_argument("--task", default="logistic_regression",
                   choices=["logistic_regression", "linear_regression",
                            "poisson_regression", "smoothed_hinge_loss_linear_svm"])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--config", default=None,
                   help="GameTrainingConfig JSON file (enables GAME path)")
    p.add_argument("--optimizer", default="lbfgs", choices=["lbfgs", "tron"])
    p.add_argument("--regularization", default="l2",
                   choices=["none", "l1", "l2", "elastic_net"])
    p.add_argument("--elastic-net-alpha", type=float, default=None)
    p.add_argument("--reg-weights", default="1.0",
                   help="comma-separated lambda sweep (legacy path)")
    p.add_argument("--max-iterations", type=int, default=None)
    p.add_argument("--tolerance", type=float, default=None)
    p.add_argument("--normalization", default="none",
                   choices=["none", "scale_with_standard_deviation",
                            "scale_with_max_magnitude", "standardization"])
    p.add_argument("--evaluators", default=None,
                   help="comma-separated, e.g. AUC,RMSE,PRECISION@K:10:userId")
    p.add_argument("--compute-variances", action="store_true")
    p.add_argument("--x64", action="store_true", help="float64 (parity runs)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--mesh", default="auto",
                   help="'auto' = all local devices on the data axis, 'none' "
                        "= single device, or 'DxF' (e.g. '4x2' = 4-way data "
                        "x 2-way feature sharding; F > 1 trains dense fixed "
                        "effects on the feature-axis consensus-ADMM lane).  "
                        "On a multi-process run the device list is GLOBAL "
                        "(every host's devices, processes contiguous on the "
                        "data axis)")
    # multi-host bring-up (parallel/multihost.py): all three fall back to
    # $PHOTON_COORDINATOR / $PHOTON_NUM_PROCESSES / $PHOTON_PROCESS_ID so
    # pod launchers can export identity instead of templating argv
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multi-host runs: process 0's coordination "
                        "endpoint (jax.distributed); required when "
                        "--num-processes > 1")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total processes in this run (1 = single-process, "
                        "the default); a relaunch after a lost worker "
                        "passes the SMALLER survivor count and resumes "
                        "from --checkpoint-dir")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's id in [0, num-processes); process "
                        "0 owns every durable write (checkpoints, models, "
                        "summaries)")
    p.add_argument("--data-validation", default="full",
                   choices=["full", "sample", "disabled"],
                   help="input sanity-check intensity (reference: "
                        "DataValidationType VALIDATE_FULL/SAMPLE/DISABLED)")
    p.add_argument("--no-weight-check", action="store_true",
                   help="allow rows with weights <= 0 (the cheap rejection "
                        "otherwise runs even under --data-validation "
                        "disabled, like the reference's separate checkData "
                        "flag)")
    # hyperparameter tuning (reference: GameTrainingParams tuning mode +
    # Driver.runHyperparameterTuning, cli/game/training/Driver.scala:337-373)
    p.add_argument("--tuning", default="none",
                   choices=["none", "random", "bayesian"])
    p.add_argument("--tuning-iterations", type=int, default=10)
    p.add_argument("--tuning-range", default="-3,3",
                   help="log10 lambda search range 'lo,hi' per coordinate")
    p.add_argument("--sweep-seed", type=int, default=None,
                   help="seed for the hyperparameter search (candidate "
                        "draws + GP slice sampler): a fixed seed reproduces "
                        "the candidate sequence bit-identically; default = "
                        "the training config's seed")
    p.add_argument("--warm-start", action="store_true",
                   help="initialize each grid combo / tuning refit from the "
                        "previous (best) model (reference: use-warm-start, "
                        "GameTrainingParams.scala:197)")
    p.add_argument("--event-listener", action="append", default=[],
                   help="dotted class path of an EventListener to register "
                        "(repeatable; reference: Driver.scala:108-118)")
    p.add_argument("--profile", action="store_true",
                   help="record a jax.profiler trace of the training run "
                        "into <output-dir>/profile (the TPU-native "
                        "replacement for the reference's Timed/Spark event "
                        "log; view with TensorBoard or xprof)")
    p.add_argument("--trace-out", default=None, metavar="TRACE.json",
                   help="arm the telemetry span tracer and write a Chrome-"
                        "trace/Perfetto JSON timeline of the fit (outer "
                        "iterations -> coordinate visits -> solves / chunk "
                        "staging / checkpoint writes, with fault/"
                        "quarantine/recovery events attached to their "
                        "spans); open at https://ui.perfetto.dev.  "
                        "Disarmed (the default) the instrumentation is a "
                        "module-global None check — zero overhead")
    p.add_argument("--run-log", default=None, metavar="RUN.jsonl",
                   help="JSONL run log: one line per finished span and "
                        "instant event (EventEmitter events, fault "
                        "injections, quarantine rollbacks, checkpoint "
                        "recoveries), correlated by span id with "
                        "--trace-out; arms the tracer like --trace-out")
    p.add_argument("--no-compile-cache", action="store_true",
                   help="disable the persistent XLA compilation cache (on "
                        "by default so repeat invocations skip compiles; "
                        "cache dir: <repo>/.jax_cache or $PHOTON_JAX_CACHE)")
    p.add_argument("--model-format", default="npz",
                   choices=["npz", "avro", "reference"],
                   help="best-model output format; avro writes the "
                        "reference's BayesianLinearModelAvro / "
                        "LatentFactorAvro interchange records; reference "
                        "writes the Scala reference's own directory layout "
                        "(part-*.avro + id-info) that photon-ml itself "
                        "can load")
    p.add_argument("--initial-model-dir", default=None,
                   help="warm-start every coordinate this model covers "
                        "(npz, avro, or a reference-layout directory that "
                        "actual photon-ml wrote); beyond the reference, "
                        "whose warm start is intra-sweep only")
    p.add_argument("--checkpoint-dir", default=None,
                   help="persist the model after every outer coordinate-"
                        "descent iteration and resume from the latest "
                        "record on restart; sweeps checkpoint per grid "
                        "combo (the reference restarts failed jobs from "
                        "scratch)")
    p.add_argument("--hbm-budget", default=None,
                   help="device-memory residency budget, e.g. '8GB', "
                        "'512MB', or raw bytes — PER DEVICE on a mesh "
                        "(blocks shard 1/D per chip, so aggregate fit size "
                        "scales with fleet HBM).  When the training "
                        "coordinates' device blocks can't all fit: "
                        "fixed-effect shards over budget stream in double-"
                        "buffered host->device chunks (sharded over the "
                        "mesh when one is active), and inactive "
                        "coordinates' blocks are evicted between "
                        "coordinate-descent visits (out-of-core training — "
                        "fit size bounded by host memory, not HBM; see "
                        "COMPONENTS.md 'Memory modes').  Overrides the "
                        "config file's hbm_budget_bytes")
    p.add_argument("--timing-mode", default="pipelined",
                   choices=["pipelined", "strict"],
                   help="pipelined (default): device work for the next "
                        "coordinate is enqueued while the previous one's "
                        "bookkeeping is in flight — objectives/metrics "
                        "fetched in one batched readback per outer "
                        "iteration, checkpoints written by a background "
                        "thread.  strict: sync after every update (same "
                        "math bit-for-bit; per-phase timings stay "
                        "attributable to the device work they launched)")
    p.add_argument("--fault-plan", default=None,
                   help="ARM FAULT INJECTION (testing/chaos runs only): "
                        "FaultPlan JSON (inline or @file) of named "
                        "injection sites x trigger hits/probabilities "
                        "(utils/faults.py; same format as the "
                        "PHOTON_FAULT_PLAN env var, which also works).  "
                        "With no plan the injection sites are zero-"
                        "overhead no-ops.  On SIGTERM/SIGINT the trainer "
                        "exits RESUMABLY (status 75, EX_TEMPFAIL) after "
                        "finishing the in-flight coordinate update and "
                        "making the newest checkpoint durable")
    return p


def make_mesh_from_arg(mesh_arg: str):
    """'auto' | 'none' | 'DxF' -> Mesh or None.  The default builds a mesh
    over ALL local devices — the distributed path IS the product path
    (the reference driver is always distributed: Driver.scala:50-505)."""
    if mesh_arg == "none":
        return None
    from photon_ml_tpu.parallel import make_mesh
    if mesh_arg == "auto":
        return make_mesh()
    d, _, f = mesh_arg.partition("x")
    return make_mesh(int(d), int(f) if f else 1)


def resolve_avro_paths(path: str):
    """'.avro' file, directory of .avro files, or glob -> sorted paths, or
    None when `path` is not an Avro input.  A directory or glob that yields
    NO .avro files is an explicit error, not a silent fall-through."""
    import glob as _glob
    if os.path.isdir(path):
        found = sorted(_glob.glob(os.path.join(path, "*.avro")))
        if not found:
            raise SystemExit(f"no .avro files found in directory {path!r}")
        return found
    if "*" in path or "?" in path:
        found = sorted(p for p in _glob.glob(path) if p.endswith(".avro"))
        if not found:
            raise SystemExit(f"glob {path!r} matched no .avro files")
        return found
    if path.endswith(".avro"):
        return [path]
    return None


def parse_byte_size(arg) -> int:
    """'8GB' / '512MB' / '1.5g' / '4096' -> bytes (decimal units, like
    accelerator spec sheets)."""
    if arg is None:
        return None
    s = str(arg).strip().lower()
    units = {"tb": 1e12, "t": 1e12, "gb": 1e9, "g": 1e9, "mb": 1e6,
             "m": 1e6, "kb": 1e3, "k": 1e3, "b": 1.0}
    for suffix, mult in units.items():
        if s.endswith(suffix):
            num = s[: -len(suffix)].strip()
            break
    else:
        num, mult = s, 1.0
    try:
        value = float(num) * mult
    except ValueError:
        raise SystemExit(f"--hbm-budget: cannot parse {arg!r} (expected "
                         "e.g. '8GB', '512MB', or raw bytes)")
    if value <= 0:
        raise SystemExit(f"--hbm-budget must be positive, got {arg!r}")
    return int(value)


def _load_json_arg(arg: str):
    """Shared 'inline JSON or @file' convention for CLI JSON flags."""
    if arg.startswith("@"):
        with open(arg[1:]) as f:
            return json.loads(f.read())
    return json.loads(arg)


def parse_input_columns(arg):
    """JSON column remap -> InputColumnNames (reference: InputColumnsNames
    remappable response/offset/weight/uid names)."""
    from photon_ml_tpu.data.game_data import InputColumnNames
    if arg is None:
        return InputColumnNames()
    import dataclasses as _dc
    m = _load_json_arg(arg)
    allowed = {f.name for f in _dc.fields(InputColumnNames)}
    if not isinstance(m, dict) or not all(
            isinstance(v, str) and v for v in m.values()):
        raise SystemExit("--input-columns must be a JSON object mapping "
                         "column roles to non-empty string column names")
    bad = set(m) - allowed
    if bad:
        raise SystemExit(f"--input-columns: unknown keys {sorted(bad)} "
                         f"(allowed: {sorted(allowed)})")
    return InputColumnNames(**m)


def parse_feature_shard_map(arg):
    """JSON inline or @file -> {shard: [bags]}; default single-shard merge
    of the TrainingExampleAvro 'features' bag."""
    if arg is None:
        return {"global": ["features"]}
    m = _load_json_arg(arg)
    if not isinstance(m, dict) or not all(
            isinstance(v, list) and v for v in m.values()):
        raise SystemExit("--feature-shard-map must be a JSON object mapping "
                         "shard name -> non-empty list of bag fields")
    return m


def _load_dataset(path: str, task: str, args=None, train_dataset=None,
                  date_range=None, days_ago=None, pinned_maps=None):
    """`train_dataset` pins a validation read to the TRAINING feature/entity
    spaces: separately-scanned Avro validation data would otherwise build
    its own sorted vocabularies and silently misalign columns with the
    trained coefficients.  `date_range`/`days_ago` expand the path's
    daily/YYYY/MM/DD tree (each input resolves its own range, reference:
    GameDriver.pathsForDateRange)."""
    import glob as _glob

    from photon_ml_tpu.data import build_game_dataset, read_libsvm
    from photon_ml_tpu.data.game_data import load_game_dataset
    if path.endswith(".libsvm") or path.endswith(".txt"):
        if pinned_maps is not None:
            raise SystemExit(
                "a pinned feature space (--index-map-dir / "
                "--selected-features) requires Avro training input: LIBSVM "
                "features are positional, not (name, term)-keyed")
        x, y = read_libsvm(path)
        return build_game_dataset(y, {"global": x})
    if date_range or days_ago:
        from photon_ml_tpu.data.date_range import paths_for_date_range
        day_dirs = paths_for_date_range(path, date_range, days_ago)
        # a day dir without .avro files (e.g. only a _SUCCESS marker) is
        # skipped, matching the reference's errorOnMissing=false posture;
        # only a range yielding NOTHING is an error
        avro_paths = []
        for d in day_dirs:
            avro_paths.extend(sorted(_glob.glob(os.path.join(d, "*.avro"))))
        if not avro_paths:
            raise SystemExit(
                f"no .avro files under any day directory of {path!r} "
                "for the requested date range")
    else:
        avro_paths = resolve_avro_paths(path)
    if avro_paths is not None:
        # reference: AvroDataReader.readMerged + GameConverters — the
        # primary input path of the GAME training driver
        from photon_ml_tpu.data.avro_game import read_game_examples
        shard_map = parse_feature_shard_map(
            getattr(args, "feature_shard_map", None) if args else None)
        id_cols = (getattr(args, "id_columns", None) or "") if args else ""
        if train_dataset is not None and not train_dataset.index_maps:
            # a libsvm/npz training input carries no (name,term) index maps,
            # so an Avro validation read has nothing to pin its columns to —
            # the scanned vocabulary would silently misalign with the
            # trained coefficients
            raise SystemExit(
                "Avro validation data requires the training input to carry "
                "feature index maps (train from Avro, or from an npz "
                "GameDataset saved with index maps); the training dataset "
                "has none, so validation columns cannot be aligned with the "
                "trained model's feature space")
        result = read_game_examples(
            avro_paths, shard_map,
            id_columns=[c for c in id_cols.split(",") if c],
            columns=parse_input_columns(
                getattr(args, "input_columns", None) if args else None),
            index_maps=(pinned_maps if pinned_maps is not None
                        else train_dataset.index_maps or None
                        if train_dataset is not None else None),
            entity_vocabs=(train_dataset.entity_vocabs or None
                           if train_dataset is not None else None))
        return result.dataset
    if pinned_maps is not None:
        raise SystemExit(
            "a pinned feature space (--index-map-dir / --selected-features) "
            "requires Avro training input; an npz GameDataset already "
            "carries its feature spaces")
    return load_game_dataset(path)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # stderr stays quiet unless --verbose (configured only when no host
    # application has set up logging; basicConfig is a no-op otherwise and
    # we must not touch a host's handlers or levels)
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(message)s", stream=sys.stderr)
        # gate stderr on the HANDLER we just created: package INFO records
        # propagate past the root logger's level, so the handler level is
        # what actually keeps stderr quiet without --verbose
        for h in logging.getLogger().handlers:
            h.setLevel(logging.INFO if args.verbose else logging.WARNING)
    # persisted job log: the package logger always captures INFO into
    # <output-dir>/training.log regardless of the host/root configuration
    # (reference: PhotonLogger writes the job log next to the job output on
    # HDFS, photon-lib/.../util/PhotonLogger.scala:36-521)
    pkg_logger = logging.getLogger("photon_ml_tpu")
    prev_level = pkg_logger.level
    pkg_logger.setLevel(logging.INFO)
    os.makedirs(args.output_dir, exist_ok=True)
    # multi-process runs share one output dir: each non-primary process
    # logs to its own file so N writers never interleave one stream
    from photon_ml_tpu.parallel import multihost
    _pid = (args.process_id if args.process_id is not None
            else multihost.process_index())
    _log_name = "training.log" if _pid == 0 else f"training.proc{_pid}.log"
    _fh = logging.FileHandler(os.path.join(args.output_dir, _log_name))
    _fh.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(message)s"))
    _fh.setLevel(logging.INFO)
    pkg_logger.addHandler(_fh)
    log = logging.getLogger("photon_ml_tpu.train")
    try:
        return _run(args, log)
    finally:
        # main() is a callable API: don't leak this job's log handler into
        # the next in-process call, whatever stage raised
        pkg_logger.removeHandler(_fh)
        pkg_logger.setLevel(prev_level)
        _fh.close()


def _run(args, log) -> int:
    log.info("args: %s", vars(args))

    import jax
    if args.x64:
        jax.config.update("jax_enable_x64", True)

    # multi-host bring-up (parallel/multihost.py) — BEFORE anything touches
    # jax devices: jax.distributed can only join a cluster on a fresh
    # backend.  Identity falls back to $PHOTON_* env vars; a single-process
    # invocation with none of the flags/env set skips all of this.
    from photon_ml_tpu.parallel import multihost
    watchdog = None
    if (args.coordinator is not None or args.num_processes is not None
            or args.process_id is not None
            or os.environ.get(multihost.ENV_COORDINATOR)
            or os.environ.get(multihost.ENV_NUM_PROCESSES)):
        multihost.initialize(args.coordinator, args.num_processes,
                             args.process_id)
    if multihost.active():
        if args.validation_data or args.tuning != "none":
            raise SystemExit(
                "--validation-data/--tuning are not supported on a "
                "multi-process run yet: the validation plane scores with "
                "process-LOCAL arrays, which cannot mix with the global "
                "training placements.  Validate the saved model in a "
                "separate single-process job.")
        if args.mesh == "none":
            raise SystemExit(
                "--mesh none contradicts a multi-process run: without a "
                "global mesh each process would train its own local copy")
        watchdog = multihost.WorkerWatchdog(
            args.output_dir,
            interval_s=float(os.environ.get(
                "PHOTON_HEARTBEAT_INTERVAL", 0.5)),
            timeout_s=float(os.environ.get(
                "PHOTON_HEARTBEAT_TIMEOUT", 10.0)),
            escalate_s=float(os.environ.get(
                "PHOTON_HEARTBEAT_ESCALATE", 10.0))).start()
        multihost.set_watchdog(watchdog)
        log.info("multihost: process %d/%d, watchdog armed "
                 "(timeout %.1fs, escalate %.1fs)",
                 multihost.process_index(), multihost.process_count(),
                 watchdog.timeout_s, watchdog.escalate_s)

    # fault containment control plane (utils/faults.py): an env- or
    # flag-armed injection plan (chaos/testing runs), and SIGTERM/SIGINT
    # graceful preemption — finish the in-flight coordinate update, make
    # the newest checkpoint durable, exit with the resumable status 75
    from photon_ml_tpu.utils import faults
    fault_plan = faults.install_from_env()
    if args.fault_plan:
        fault_plan = faults.FaultPlan.from_dict(
            _load_json_arg(args.fault_plan))
        faults.install_plan(fault_plan)
        log.warning("fault plan ACTIVE from --fault-plan: %d spec(s)",
                    len(fault_plan.specs))

    # telemetry (photon_ml_tpu/telemetry): the span tracer arms only when
    # a timeline was asked for — disarmed it is a module-global None check
    # on every instrumented path.  The metrics registry is always live.
    from photon_ml_tpu import telemetry
    tracer = None
    if args.trace_out or args.run_log:
        tracer = telemetry.install(run_log=args.run_log, proc="train")
        log.info("telemetry armed: trace_out=%s run_log=%s",
                 args.trace_out, args.run_log)

    # persistent compile cache + honest compile accounting (the reference
    # pays no compile cost — JVM/Breeze interprets; a warm cache is our
    # equivalent posture, and compile_s in the summary proves it worked)
    from photon_ml_tpu.utils.jax_cache import (CompileTimeTracker,
                                               enable_persistent_cache)
    compile_tracker = CompileTimeTracker().install()
    cache_dir = None
    if not args.no_compile_cache:
        cache_dir = enable_persistent_cache()
        log.info("persistent compile cache: %s", cache_dir)

    from photon_ml_tpu.game import GameEstimator, GameTrainingConfig
    from photon_ml_tpu.game.config import (FixedEffectCoordinateConfig,
                                           GLMOptimizationConfig)
    from photon_ml_tpu.models.io import save_game_model
    from photon_ml_tpu.ops.normalization import NormalizationType
    from photon_ml_tpu.optim import (OptimizerConfig, OptimizerType,
                                     RegularizationContext, RegularizationType)

    t0 = time.time()
    pinned_maps = None
    if args.selected_features:
        # reference: the legacy driver's selected-features file (GLMSuite
        # selectedFeaturesFile) — a FeatureAvro list freezing the feature
        # space to exactly those (name, term) keys + intercept
        if args.index_map_dir:
            raise SystemExit("--selected-features and --index-map-dir are "
                             "exclusive (both pin the feature space)")
        if args.feature_shard_map:
            raise SystemExit("--selected-features applies to the default "
                             "single-shard ingest only (the legacy driver's "
                             "scope); build maps with cli.index for "
                             "multi-shard jobs")
        from photon_ml_tpu.data.avro_codec import read_container
        from photon_ml_tpu.data.index_map import IndexMap, feature_key
        keys = [feature_key(r["name"], r.get("term") or "")
                for r in read_container(args.selected_features)]
        if not keys:
            raise SystemExit(f"--selected-features {args.selected_features!r}"
                             " names no features")
        pinned_maps = {"global": IndexMap.from_keys(keys)}
        log.info("feature space restricted to %d selected features",
                 len(keys))
    if args.index_map_dir:
        # frozen shared feature space (reference: FeatureIndexingJob +
        # PalDBIndexMapLoader): jobs trained against the same prebuilt maps
        # are guaranteed identical feature dimensions and key->column
        # assignment, whatever data slice each one saw
        from photon_ml_tpu.data.index_map import IndexMapCollection
        pinned_maps = IndexMapCollection.load(args.index_map_dir).shards
        log.info("pinned feature spaces from %s: %s", args.index_map_dir,
                 {s: m.size for s, m in pinned_maps.items()})
    train = _load_dataset(args.train_data, args.task, args,
                          date_range=args.input_date_range,
                          days_ago=args.input_days_ago,
                          pinned_maps=pinned_maps)
    val = (_load_dataset(args.validation_data, args.task, args,
                         train_dataset=train,
                         date_range=args.validation_date_range,
                         days_ago=args.validation_days_ago)
           if args.validation_data else None)
    ingest_s = time.time() - t0
    log.info("loaded train: %d rows, shards %s", train.num_rows,
             {s: x.shape[1] for s, x in train.feature_shards.items()})
    print(f"loaded train: {train.num_rows} rows, shards "
          f"{ {s: x.shape[1] for s, x in train.feature_shards.items()} }",
          file=sys.stderr)

    # reference: Driver.run -> DataValidators.sanityCheckDataFrameForTraining
    # (validate against the task actually trained: the config file's
    # task_type wins over --task on the GAME path)
    from photon_ml_tpu.data.validators import validate_game_dataset
    task = args.task
    if args.config:
        with open(args.config) as f:
            task = GameTrainingConfig.from_json(f.read()).task_type
    validate_game_dataset(train, task, args.data_validation,
                          check_weights=not args.no_weight_check)
    if val is not None:
        validate_game_dataset(val, task, args.data_validation,
                              check_weights=not args.no_weight_check)

    if args.save_feature_stats and multihost.is_primary():
        # reference: cli/game/training/Driver.calculateAndSaveFeatureShardStats
        # (Driver.scala:297) — per-shard BasicStatisticalSummary persisted
        # next to the job output (process 0 only on a multi-process run:
        # every process sees the same full host dataset)
        from photon_ml_tpu.data.stats import BasicStatisticalSummary
        stats_dir = os.path.join(args.output_dir, "feature-stats")
        os.makedirs(stats_dir, exist_ok=True)
        for shard, x in train.feature_shards.items():
            summary = (BasicStatisticalSummary.from_sparse(x, train.weights)
                       if hasattr(x, "tocsr") and not isinstance(x, np.ndarray)
                       else BasicStatisticalSummary.from_features(
                           np.asarray(x), train.weights))
            payload = summary.to_dict()
            imap = (train.index_maps or {}).get(shard)
            if imap is None:
                log.info("shard %r carries no index map: JSON stats only "
                         "(FeatureSummarizationResultAvro keys features by "
                         "name/term)", shard)
            else:
                payload["feature_keys"] = [str(k) for k in imap.index_to_key]
                # the reference's own interchange format alongside the JSON
                # (FeatureSummarizationResultAvro, one record per feature;
                # ModelProcessingUtils.writeBasicStatistics)
                from photon_ml_tpu.data.avro_io import write_feature_stats_avro
                avro_dir = os.path.join(stats_dir, shard)
                os.makedirs(avro_dir, exist_ok=True)
                write_feature_stats_avro(
                    os.path.join(avro_dir, "part-00000.avro"), summary, imap)
            with open(os.path.join(stats_dir, f"{shard}.json"), "w") as f:
                json.dump(payload, f)
        log.info("feature stats saved to %s", stats_dir)

    mesh = make_mesh_from_arg(args.mesh)
    if mesh is not None:
        from photon_ml_tpu.parallel.mesh import FEATURE_AXIS
        lanes = (" (feature axis > 1: dense fixed effects use the "
                 "consensus-ADMM lane)"
                 if mesh.shape.get(FEATURE_AXIS, 1) > 1 else "")
        print(f"mesh: {dict(mesh.shape)} over {len(mesh.devices.ravel())} "
              f"devices{lanes}", file=sys.stderr)
    evaluator_specs = args.evaluators.split(",") if args.evaluators else None

    # event hooks (reference: Driver.scala:108-118 registers listeners by
    # class name; PhotonSetupEvent carries the run params)
    from photon_ml_tpu.utils.events import EventEmitter, SetupEvent
    emitter = EventEmitter() if args.event_listener else None
    if emitter is not None:
        for dotted in args.event_listener:
            emitter.register_listener_class(dotted)
        emitter.send_event(SetupEvent(params=vars(args)))

    profile_ctx = None
    if args.profile:
        profile_dir = os.path.join(args.output_dir, "profile")
        os.makedirs(profile_dir, exist_ok=True)
        profile_ctx = jax.profiler.trace(profile_dir)
        profile_ctx.__enter__()
        print(f"profiling to {profile_dir}", file=sys.stderr)

    preempt_guard = faults.GracefulPreemption()
    preempt_guard.__enter__()
    try:
        initial_model = None
        if args.initial_model_dir:
            # cross-job warm start (BEYOND the reference, whose warm start
            # is intra-sweep only): any supported layout loads here,
            # including a model directory actual photon-ml wrote.  The
            # model re-keys into THIS job's feature spaces — a
            # reference-layout model stores a compact space (zeros
            # dropped), and a different data slice scans a different
            # vocabulary, so raw coefficients would misalign.
            from photon_ml_tpu.models.io import (align_game_model_to_dataset,
                                                 load_game_model,
                                                 load_model_index_maps)
            initial_model, _ = load_game_model(args.initial_model_dir)
            try:
                initial_model = align_game_model_to_dataset(
                    initial_model,
                    load_model_index_maps(args.initial_model_dir), train)
            except ValueError as e:
                raise SystemExit(f"--initial-model-dir: {e}")
            log.info("warm-starting from %s (%s)", args.initial_model_dir,
                     list(initial_model.coordinates))
        hbm_budget = parse_byte_size(args.hbm_budget)
        if args.config:
            import dataclasses as _dc
            with open(args.config) as f:
                config = GameTrainingConfig.from_json(f.read())
            if hbm_budget is not None:
                config = _dc.replace(config, hbm_budget_bytes=hbm_budget)
            results = [GameEstimator(config, mesh=mesh, emitter=emitter).fit(
                train, val, evaluator_specs,
                initial_model=initial_model,
                checkpoint_dir=args.checkpoint_dir,
                timing_mode=args.timing_mode)]
        else:
            # legacy single-GLM path: one FE coordinate, lambda sweep, best by
            # first validation evaluator (reference: Driver stage machine +
            # ModelSelection)
            reg = RegularizationContext(RegularizationType(args.regularization),
                                        args.elastic_net_alpha)
            opt = OptimizerConfig(optimizer=OptimizerType(args.optimizer),
                                  max_iterations=args.max_iterations,
                                  tolerance=args.tolerance)
            weights = [float(w) for w in args.reg_weights.split(",")]
            grid = {"fixed": [GLMOptimizationConfig(optimizer=opt, regularization=reg,
                                                    regularization_weight=w)
                              for w in sorted(weights, reverse=True)]}
            config = GameTrainingConfig(
                task_type=args.task,
                coordinates={"fixed": FixedEffectCoordinateConfig(
                    "global", GLMOptimizationConfig(optimizer=opt, regularization=reg),
                    normalization=NormalizationType(args.normalization))},
                updating_sequence=["fixed"],
                hbm_budget_bytes=hbm_budget)
            results = GameEstimator(config, mesh=mesh, emitter=emitter).fit_grid(
                train, grid, val, evaluator_specs, warm_start=args.warm_start,
                checkpoint_dir=args.checkpoint_dir,
                initial_model=initial_model, timing_mode=args.timing_mode)

        if args.tuning != "none":
            # reference: Driver.runHyperparameterTuning — searcher seeded with
            # the grid results, evaluation = refit with the candidate lambdas
            if val is None:
                raise SystemExit("--tuning requires --validation-data")
            from photon_ml_tpu.hyperparameter import (
                GameEstimatorEvaluationFunction, GaussianProcessSearch, RandomSearch)
            fn = GameEstimatorEvaluationFunction(
                GameEstimator(config, mesh=mesh, emitter=emitter), train, val,
                evaluator_specs, scale="log", warm_start=args.warm_start,
                initial_model=initial_model)
            if args.warm_start:
                for r in results:
                    if r.validation:
                        fn.observe(r)
            lo, hi = (float(v) for v in args.tuning_range.split(","))
            ranges = [(lo, hi)] * fn.num_params
            spec0 = results[0].validation_specs[0]
            # --sweep-seed pins the WHOLE search chain (candidate draws,
            # GP estimator init, slice sampler) independently of the
            # training seed: a fixed value reproduces the candidate
            # sequence bit-identically
            sweep_seed = (args.sweep_seed if args.sweep_seed is not None
                          else config.seed)
            if args.tuning == "bayesian":
                search = GaussianProcessSearch(ranges, fn, spec0.evaluator,
                                               seed=sweep_seed)
            else:
                search = RandomSearch(ranges, fn, seed=sweep_seed)
            prior = [r for r in results if r.validation]
            results = results + search.find(args.tuning_iterations, prior)

        from photon_ml_tpu.game.estimator import select_best_result
        best = select_best_result(results)
        os.makedirs(args.output_dir, exist_ok=True)
        if multihost.is_primary():
            # process 0 owns every durable artifact (photonlint PH014);
            # peers trained the SAME model — GSPMD reductions leave the
            # coefficients replicated — so one writer loses nothing
            save_game_model(best.model,
                            os.path.join(args.output_dir, "best"),
                            config=best.config,
                            index_maps=train.index_maps or None,
                            format=args.model_format)
        # per-coordinate inner-solver accounting (SolveResult already
        # carried iterations + ConvergenceReason; the fit summary now
        # surfaces them instead of dropping them on the floor)
        solver_diag = best.descent.solver_diagnostics()
        summary = {
            "task": args.task,
            "train_rows": train.num_rows,
            "ingest_s": round(ingest_s, 2),
            "num_configs": len(results),
            "final_objective": best.objective_history[-1],
            "validation": best.validation,
            "solver_iterations_total": best.descent.total_iterations(),
            "solver_diagnostics": solver_diag,
            # fault containment accounting: quarantine events (rollbacks /
            # tightened retries / freezes), coordinates left frozen, how
            # the checkpoint was recovered at resume, and — on chaos runs —
            # the injection plan's per-site fire counts
            "containment_events": best.descent.containment_events,
            "frozen_coordinates": best.descent.frozen_coordinates,
            "checkpoint_recovery": best.checkpoint_recovery,
            "fault_report": (fault_plan.report() if fault_plan is not None
                             else None),
            "wall_s": round(time.time() - t0, 2),
            "timing_mode": args.timing_mode,
            # HBM residency accounting (None budget = unbounded/resident;
            # PER-DEVICE semantics on a mesh — accounting carries
            # per_device/data_devices)
            "hbm_budget_bytes": hbm_budget,
            "hbm_residency": getattr(best, "residency", None),
            # multi-chip accounting: mesh axes + cold/warm staged bytes
            # (mesh_transfer proves a warm iteration moves only
            # coefficients/offsets, never the dataset)
            "mesh": dict(mesh.shape) if mesh is not None else None,
            "mesh_transfer": getattr(best, "mesh_transfer", None),
            # multi-host accounting: identity + whether the mesh spans
            # processes (mesh_transfer bytes above are PER-PROCESS there)
            "multihost": ({"num_processes": multihost.process_count(),
                           "process_id": multihost.process_index()}
                          if multihost.active() else None),
            "host_blocked_s": round(
                getattr(getattr(best.descent, "timings", None),
                        "host_blocked_total", lambda: 0.0)(), 3),
            "compile_s": round(compile_tracker.seconds, 2),
            "compile_count": compile_tracker.count,
            "compile_cache": cache_dir,
            # the unified telemetry surface: registry counters/gauges/
            # histograms (stream/mesh/checkpoint/quarantine/retrace
            # accounting) + tracer record counts when armed
            "telemetry": telemetry.snapshot(),
            "trace_out": args.trace_out,
            "output": os.path.join(args.output_dir, "best"),
        }
        if multihost.is_primary():
            with open(os.path.join(args.output_dir,
                                   "training-summary.json"), "w") as f:
                json.dump(summary, f, indent=2)
        log.info("summary: %s", summary)
        for coord, d in solver_diag.items():
            log.info("solver %-16s solves=%d iterations=%d reasons=%s "
                     "caps=%s", coord, d["solves"], d["iterations"],
                     d["reasons"], d["iteration_caps"])
            if "stream" in d:
                st = d["stream"]
                log.info("stream %-16s staged=%.1f MB chunks=%d "
                         "local_epochs=%d examples=%d "
                         "examples/staged-byte=%.4f", coord,
                         st["total_bytes"] / 1e6, st["chunks_staged"],
                         st["local_epochs"], st["examples_processed"],
                         st["examples_per_staged_byte"])
        if mesh is not None and summary["mesh_transfer"] is not None:
            acct = summary["hbm_residency"] or {}
            log.info(
                "mesh %s: staged %.1f MB cold / %.1f MB warm; per-device "
                "peak %.1f MB (budget %s)", dict(mesh.shape),
                summary["mesh_transfer"]["cold_bytes"] / 1e6,
                summary["mesh_transfer"]["warm_bytes"] / 1e6,
                acct.get("peak_tracked_bytes", 0) / 1e6,
                ("%.1f MB" % (acct["budget_bytes"] / 1e6)
                 if acct.get("budget_bytes") else "unbounded"))
        for name, t in getattr(best.descent, "timings", {}).items():
            log.info("phase %s: %.3fs", name, t)
        print(json.dumps(summary))
        return 0
    except faults.Preempted as e:
        # graceful preemption (SIGTERM/SIGINT): the in-flight coordinate
        # update finished and the newest checkpoint record is durable —
        # report resumability and exit with the DISTINCT status 75
        # (EX_TEMPFAIL) so schedulers relaunch the same command
        payload = {
            "preempted": True,
            "completed_iterations": e.completed_iterations,
            "resumable": e.checkpointed,
            "checkpoint_dir": e.checkpoint_dir,
            "exit_status": faults.EXIT_PREEMPTED,
            "lost_worker": (watchdog.lost_process
                            if watchdog is not None else None),
            "wall_s": round(time.time() - t0, 2),
        }
        log.warning("preempted: %s", e)
        if multihost.is_primary():
            with open(os.path.join(args.output_dir,
                                   "training-summary.json"), "w") as f:
                json.dump(payload, f, indent=2)
        print(json.dumps(payload))
        return faults.EXIT_PREEMPTED
    except Exception:
        # a peer died mid-collective: gloo/XLA surface that as an opaque
        # RuntimeError in the MAIN thread within milliseconds — typically
        # BEFORE the watchdog's heartbeat timeout has elapsed — so poll
        # the peer heartbeats synchronously to tell a dead peer apart
        # from a genuine local crash.  With a confirmed loss this process
        # is a SURVIVOR — exit with the resumable status 75 (checkpoint
        # state is durable + manifest-consistent), not a crash.
        lost = watchdog.confirm_lost() if watchdog is not None else None
        if lost is not None:
            log.error("multihost: collective failed after losing worker "
                      "%d — exiting resumably (status %d)",
                      lost, faults.EXIT_PREEMPTED, exc_info=True)
            print(json.dumps({
                "preempted": True, "resumable": True,
                "lost_worker": lost,
                "exit_status": faults.EXIT_PREEMPTED}))
            return faults.EXIT_PREEMPTED
        raise
    finally:
        preempt_guard.__exit__(None, None, None)
        if profile_ctx is not None:
            profile_ctx.__exit__(None, None, None)
        if tracer is not None:
            # export on EVERY path (success, preemption, failure): a
            # timeline of the run that died is the one you want most
            telemetry.shutdown()
            if args.trace_out:
                try:
                    info = telemetry.write_chrome_trace(args.trace_out)
                    log.info("chrome trace written: %s", info)
                    print(f"trace written to {args.trace_out} "
                          f"({info['events']} events) — open at "
                          "https://ui.perfetto.dev", file=sys.stderr)
                except Exception:
                    log.exception("trace export failed")
        # listeners flush buffered events in close() — run even when
        # training/validation/tuning raises
        if emitter is not None:
            emitter.clear_listeners()
        # multihost teardown LAST (stops the watchdog, leaves
        # jax.distributed, resets identity) so an in-process caller can
        # run again; idempotent no-op on single-process runs
        multihost.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
