"""Standalone feature-indexing job: prebuild frozen feature spaces.

Rebuild of the reference's FeatureIndexingJob (photon-client/.../
FeatureIndexingJob.scala:56-307): scan Avro training data's feature bags,
build one deterministic IndexMap per feature shard, and save them so
SEPARATE jobs (training on different data slices, offline scoring,
diagnostics) share a single frozen feature space.  npz map files replace
the reference's partitioned PalDB stores (documented descope); the train
CLI consumes the output via --index-map-dir.

  python -m photon_ml_tpu.cli.index --data 'daily/*/part-*.avro' \
      --feature-shard-map '{"global": ["features"]}' --output maps/

Files are scanned ONE AT A TIME and only each file's feature-key
vocabulary crosses into Python, so peak memory is one decoded file plus
the union vocabulary — not the whole input range.
"""
from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-index")
    p.add_argument("--data", required=True,
                   help="Avro input: file, directory, or glob")
    p.add_argument("--feature-shard-map", default=None,
                   help="JSON (inline or @file) shard -> feature-bag merge "
                        "map (see cli.train); default merges the 'features' "
                        "bag into shard 'global'")
    p.add_argument("--output", required=True,
                   help="directory for the index-map collection")
    p.add_argument("--input-date-range", default=None,
                   help="yyyymmdd-yyyymmdd range over a daily/ tree")
    p.add_argument("--input-days-ago", default=None,
                   help="days-ago range, e.g. 90-1")
    return p


def scan_feature_shards(paths, feature_shard_map):
    """-> {shard: IndexMap}, one file decoded at a time; only each file's
    per-shard vocabulary is retained across files."""
    from photon_ml_tpu.data import avro_native
    from photon_ml_tpu.data.avro_codec import read_container
    from photon_ml_tpu.data.index_map import IndexMap, feature_key

    keys = {shard: set() for shard in feature_shard_map}
    for p in paths:
        cols = avro_native.read_columnar(p)
        if cols is not None:
            for shard, bags in feature_shard_map.items():
                for bag in bags:
                    if f"{bag}#count" not in cols:
                        raise ValueError(
                            f"feature bag {bag!r} (shard {shard!r}) not "
                            f"found in the records of {p}")
                file_map, _ = avro_native.resolve_feature_keys(
                    [cols[f"{bag}.name"] for bag in bags],
                    [cols[f"{bag}.term"] for bag in bags], None)
                keys[shard].update(map(str, file_map.index_to_key))
            continue
        # pure-Python fallback (unsupported schema shapes)
        first = True
        for rec in read_container(p):
            if first:
                for shard, bags in feature_shard_map.items():
                    for bag in bags:
                        if bag not in rec:
                            raise ValueError(
                                f"feature bag {bag!r} (shard {shard!r}) "
                                f"not found in the records of {p}")
                first = False
            for shard, bags in feature_shard_map.items():
                for bag in bags:
                    for f in rec.get(bag) or ():
                        keys[shard].add(
                            feature_key(f["name"], f.get("term", "")))
    return {shard: IndexMap.from_keys(ks) for shard, ks in keys.items()}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from photon_ml_tpu.cli.train import (parse_feature_shard_map,
                                         resolve_avro_paths)
    from photon_ml_tpu.data.index_map import IndexMapCollection

    if args.input_date_range or args.input_days_ago:
        import glob as _glob
        import os
        from photon_ml_tpu.data.date_range import paths_for_date_range
        paths = []
        for d in paths_for_date_range(args.data, args.input_date_range,
                                      args.input_days_ago):
            paths.extend(sorted(_glob.glob(os.path.join(d, "*.avro"))))
        if not paths:
            raise SystemExit(f"no .avro files under {args.data!r} for the "
                             "requested date range")
    else:
        paths = resolve_avro_paths(args.data)
        if paths is None:
            raise SystemExit(
                f"--data {args.data!r} is not an Avro input; feature "
                "indexing scans Avro feature bags "
                "(reference: FeatureIndexingJob)")

    shard_map = parse_feature_shard_map(args.feature_shard_map)
    maps = scan_feature_shards(paths, shard_map)
    IndexMapCollection(maps).save(args.output)
    print(json.dumps({"output": args.output, "files_scanned": len(paths),
                      "shards": {s: m.size for s, m in maps.items()}}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
