"""Trace tooling CLI: stitch per-process run logs into ONE fleet
timeline.

A fleet run (cli.serve --front / --replica / --publish, each with
--run-log) leaves one JSONL run log per process.  `merge` aligns their
clocks (the front's probe-derived offsets), joins the propagated request
ids (X-Photon-Trace) into connected trees, and writes a validated
Perfetto/Chrome trace with one process track per fleet member:

    python -m photon_ml_tpu.cli.trace merge \
        out/front.jsonl out/pub.jsonl out/r0.jsonl \
        --out fleet-trace.json

Open the result at https://ui.perfetto.dev.  The summary (last stdout
line, JSON) reports per-request connectivity (`requests`), the clock
offsets applied, and containment violations (children outside their
parents after alignment); exit status is non-zero when the merged trace
fails `validate_chrome_trace`.

Directories are accepted in place of files (every *.jsonl inside is
merged) — point it at the fleet's shared --run-log directory.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-ml-tpu-trace")
    sub = p.add_subparsers(dest="command", required=True)
    m = sub.add_parser(
        "merge", help="merge per-process run logs into one Perfetto "
                      "timeline")
    m.add_argument("run_logs", nargs="+", metavar="RUN.jsonl|DIR",
                   help="per-process run logs (cli.serve/cli.train "
                        "--run-log); a directory means every *.jsonl "
                        "inside it")
    m.add_argument("--out", default="fleet-trace.json",
                   metavar="TRACE.json",
                   help="merged Chrome-trace output path")
    m.add_argument("--containment-slack-ms", type=float, default=25.0,
                   help="alignment tolerance for the child-inside-parent "
                        "check (clock-probe RTT bounds the alignment "
                        "error)")
    return p


def _expand(paths) -> list:
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        else:
            out.append(p)
    if not out:
        raise SystemExit("no run logs to merge")
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command != "merge":  # pragma: no cover - argparse enforces
        raise SystemExit(f"unknown command {args.command!r}")
    from photon_ml_tpu.telemetry.distributed import merge_run_logs
    report = merge_run_logs(
        _expand(args.run_logs), out_path=args.out,
        containment_slack_s=args.containment_slack_ms / 1e3)
    summary = {k: v for k, v in report.items() if k != "trace"}
    print(json.dumps(summary), flush=True)
    if report["problems"]:
        print(f"merged trace INVALID: {report['problems'][:5]}",
              file=sys.stderr)
        return 1
    print(f"merged {len(report['processes'])} process(es), "
          f"{report['spans']} span(s) -> {args.out} — open at "
          "https://ui.perfetto.dev", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
