"""Failover front: routes scoring traffic across N replica processes.

The front is model-free — it never loads a scorer.  It holds a handle per
replica URL (the serve HTTP protocol IS the replica protocol) and:

  * PROBES   a background thread GETs each replica's /healthz every
             `probe_interval_s`; an un-ready replica (503: joining,
             draining, failed, health-gate degraded — PR 11's verdicts)
             leaves the rotation after `unhealthy_after` consecutive
             failures and re-enters after `healthy_after` successes.
             Probe payloads also carry each replica's applied seq, which
             feeds the `fleet.front_max_lag_seq` gauge.
  * ROUTES   /score and /predict round-robin over READY replicas;
             transport errors and 5xx responses fail over to the next
             replica (bounded by `max_attempts`, counted per failover);
             POST /feedback, /swap and /rollback go to the PUBLISHER
             replica only — model state changes enter the fleet through
             the replication log, never through a follower.
  * HEDGES   a scoring attempt still pending after `hedge_after_s` fires
             a duplicate at a different ready replica; first response
             wins, the loser is abandoned (bounded tail latency without
             giving up on the slow replica's in-flight work).
  * SHEDS    beyond `max_inflight` concurrently routed requests the
             front degrades to Overloaded (HTTP 429) instead of queueing
             without bound — queue collapse upstream of the replicas is
             strictly worse than explicit backpressure.
  * DRAINS   `drain(url)` stops routing to a replica, tells it to drain
             (its own /healthz flips 503 for any other front), waits for
             in-flight requests to finish, then detaches it.
  * SHARDS   when probed replicas declare entity-shard ownership
             (serve --shard K/N), scoring fans out as per-shard /margins
             legs — each leg hedged and failed over WITHIN its shard
             group — and the front re-folds the per-coordinate margins
             bit-identically to a monolithic replica
             (fleet/shards.merge_margins).  A shard with zero healthy
             replicas degrades ONLY requests touching its entities:
             `degraded_policy="partial"` folds the lost contributions as
             exactly 0.0 and stamps the response degraded,
             `"error"` fails those requests 503.  Losing a shard's last
             replica fires the shard.lost flight trigger fleet-wide.

The front's routing metrics live on its OWN MetricsRegistry (the
ServingMetrics fleet.* family is the replica-side surface): request /
failover / hedge / retry / shed counters plus ready-replica and lag
gauges, exposed as Prometheus text at the front's /metrics.
"""
from __future__ import annotations

import json
import threading
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from http.client import HTTPConnection
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import distributed, flight
from photon_ml_tpu.telemetry.export import prometheus_text
from photon_ml_tpu.telemetry.metrics import MetricsRegistry
from photon_ml_tpu.fleet.replog import decode_array
from photon_ml_tpu.fleet.shards import (ShardMergeError, ShardSpec,
                                        merge_margins, shards_touched)
from photon_ml_tpu.serving.batcher import Overloaded, ServingError
from photon_ml_tpu.utils import faults, locktrace

import dataclasses
import logging
import re
import time

logger = logging.getLogger("photon_ml_tpu")


#: the front's metric-surface parity CONTRACT (the ServingMetrics
#: SNAPSHOT_PATHS discipline): every instrument the constructor registers
#: must appear here, every path must resolve in `front_snapshot()`, and
#: tests/test_fleetobs.py diffs all three sets against the Prometheus
#: exposition — a front metric cannot land on one surface only.
FRONT_SNAPSHOT_PATHS = {
    "fleet.front_requests": ("requests",),
    "fleet.front_failovers": ("failovers",),
    "fleet.front_hedges": ("hedges",),
    "fleet.front_hedge_wins": ("hedge_wins",),
    "fleet.front_retries": ("retries",),
    "fleet.front_shed": ("shed",),
    "fleet.front_errors": ("errors",),
    "fleet.front_probe_failures": ("probe_failures",),
    "fleet.front_scrape_failures": ("scrape_failures",),
    "fleet.front_ready_replicas": ("ready_replicas",),
    "fleet.front_max_lag_seq": ("max_lag_seq",),
    "front.requests": ("requests_by_replica",),
    "fleet.shard_requests": ("shard_requests",),
    "fleet.shard_coverage": ("shard_coverage",),
    "fleet.shard_degraded": ("shard_degraded",),
}


class NoReadyReplica(ServingError):
    """Every replica is out of rotation (joining, draining, failed, or
    unreachable) — the front cannot place the request."""


@dataclasses.dataclass(frozen=True)
class FrontConfig:
    """Routing knobs (cli.serve --front maps 1:1)."""

    probe_interval_s: float = 0.25  # /healthz probe period per replica
    probe_timeout_s: float = 2.0
    unhealthy_after: int = 2        # consecutive probe failures -> out
    healthy_after: int = 1          # consecutive successes -> back in
    request_timeout_s: float = 10.0
    hedge_after_s: float = 0.25     # pending this long -> hedge a twin
    max_attempts: int = 3           # total sends per request (incl. hedges)
    max_inflight: int = 256         # routed concurrently before shedding
    # entity-sharded fleets: what a scoring request gets when a shard it
    # touches has NO healthy replica.  "partial": the lost shard's
    # random-effect contributions fold as exactly 0.0 (the unseen-entity
    # default) and the response is stamped degraded=true with the
    # affected rows; "error": the request fails 503 — correctness over
    # availability
    degraded_policy: str = "partial"


class ReplicaHandle:
    """One replica's routing state (all fields guarded by Front._lock)."""

    def __init__(self, url: str, publisher: bool = False):
        self.url = url.rstrip("/")
        parts = urlsplit(self.url)
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port or 80
        self.publisher = publisher
        self.ready = False
        self.fails = 0
        self.successes = 0
        self.draining = False
        self.detached = False
        self.inflight = 0
        self.applied_seq: Optional[int] = None
        self.last_error: Optional[str] = None
        # which entity shard this replica owns — learned from its probed
        # /healthz payload, never from static config (None: full model)
        self.shard: Optional[int] = None

    def state(self) -> Dict[str, object]:
        return {"url": self.url, "publisher": self.publisher,
                "ready": self.ready, "draining": self.draining,
                "detached": self.detached, "inflight": self.inflight,
                "applied_seq": self.applied_seq, "shard": self.shard,
                "last_error": self.last_error}


class Front:
    def __init__(self, replica_urls: List[str],
                 publisher_url: Optional[str] = None,
                 config: FrontConfig = FrontConfig(),
                 start_probes: bool = True):
        """`publisher_url` names the replica that accepts model-state
        changes (/feedback, /swap, /rollback); defaults to the first URL.
        `start_probes=False` keeps probing manual (`probe_once()`) for
        tests and the bench."""
        if not replica_urls:
            raise ValueError("a front needs at least one replica URL")
        if config.degraded_policy not in ("partial", "error"):
            raise ValueError(f"unknown degraded_policy "
                             f"{config.degraded_policy!r} "
                             "(choose 'partial' or 'error')")
        self.config = config
        self._lock = locktrace.tracked(threading.Lock(), "Front._lock")
        publisher_url = (publisher_url or replica_urls[0]).rstrip("/")
        self._handles = [ReplicaHandle(u, publisher=(u.rstrip("/") ==
                                                     publisher_url))
                         for u in replica_urls]
        self._rr = 0                             # photonlint: guarded-by=_lock
        self._inflight_total = 0                 # photonlint: guarded-by=_lock
        self.registry = MetricsRegistry()
        r = self.registry
        self._m_requests = r.counter("fleet.front_requests")
        self._m_failovers = r.counter("fleet.front_failovers")
        self._m_hedges = r.counter("fleet.front_hedges")
        self._m_hedge_wins = r.counter("fleet.front_hedge_wins")
        self._m_retries = r.counter("fleet.front_retries")
        self._m_shed = r.counter("fleet.front_shed")
        self._m_errors = r.counter("fleet.front_errors")
        self._m_probe_failures = r.counter("fleet.front_probe_failures")
        self._m_scrape_failures = r.counter("fleet.front_scrape_failures")
        self._m_ready = r.gauge("fleet.front_ready_replicas")
        self._m_max_lag = r.gauge("fleet.front_max_lag_seq")
        # per-(replica, outcome) routing visibility: which replica served,
        # failed over, shed, or was abandoned as a hedge loser
        self._m_by_replica = r.labeled_counter("front.requests",
                                               ("replica", "outcome"))
        # entity-sharded fleets: per-(shard, outcome) leg accounting, the
        # minimum per-shard healthy-replica count (-1: fleet unsharded;
        # 0: some shard is DARK — alert on this), and requests answered
        # degraded because a touched shard was dark
        self._m_shard_requests = r.labeled_counter("fleet.shard_requests",
                                                   ("shard", "outcome"))
        self._m_shard_coverage = r.gauge("fleet.shard_coverage")
        self._m_shard_coverage.set(-1.0)
        self._m_shard_degraded = r.counter("fleet.shard_degraded")
        # the fleet partition, adopted from probed replicas (highest spec
        # version wins; replicas on another spec_id leave rotation), and
        # the coordinate fold order cached off the last merged response
        self._shard_spec: Optional[ShardSpec] = None  # photonlint: guarded-by=_lock
        self._coord_meta: Optional[List[dict]] = None  # photonlint: guarded-by=_lock
        self._lost_shards: set = set()                # photonlint: guarded-by=_lock
        self._seen_shards: set = set()                # photonlint: guarded-by=_lock
        self._pool = ThreadPoolExecutor(
            max_workers=max(8, min(config.max_inflight, 64)),
            thread_name_prefix="photon-front")
        # shard-leg coordinators get their OWN small pool: a leg blocks
        # waiting on sends it submits to _pool, so running coordinators
        # there too could deadlock the pool against itself under load
        self._leg_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="photon-front-shard")
        self._closed = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None  # photonlint: guarded-by=_lock
        if start_probes:
            self.start_probes()

    # -- probing -------------------------------------------------------------

    def probe_once(self) -> Dict[str, bool]:
        """Probe every attached replica once; returns {url: ready}."""
        cfg = self.config
        results: Dict[str, bool] = {}
        with self._lock:
            handles = [h for h in self._handles if not h.detached]
        for h in handles:
            ok, payload = False, None
            t_send = time.time()
            try:
                status, body = self._send(h, "GET", "/healthz", None,
                                          cfg.probe_timeout_s)
                t_recv = time.time()
                payload = json.loads(body) if body else {}
                ok = status == 200
                err = None if ok else f"healthz {status}"
            except Exception as e:
                err = f"{type(e).__name__}: {e}"
            if ok:
                # entity-sharded fleets: the replica's /healthz declares
                # which shard it owns; a replica on an incompatible
                # partition is treated as UNHEALTHY (routing margins from
                # a different partition would merge wrong rows)
                shard_err = self._note_shard_payload(payload)
                if shard_err is not None:
                    ok, err = False, shard_err
            # every health probe doubles as an NTP-style clock probe: the
            # replica's /healthz carries its wall clock, and the minimum-
            # RTT offset estimate is what `cli.trace merge` aligns the
            # per-process timelines with
            remote_clock = (payload or {}).get("telemetry") or {}
            if remote_clock.get("wall_s") is not None:
                telemetry.event(
                    "clock_probe", url=h.url,
                    pid=int(remote_clock.get("pid", 0)),
                    proc=str(remote_clock.get("proc", "proc")),
                    offset_s=round(float(remote_clock["wall_s"])
                                   - (t_send + t_recv) / 2.0, 6),
                    rtt_s=round(t_recv - t_send, 6))
            with self._lock:
                was_ready = h.ready
                if ok:
                    h.successes += 1
                    h.fails = 0
                    if h.successes >= cfg.healthy_after:
                        h.ready = not h.draining
                    h.last_error = None
                    fleet = (payload or {}).get("fleet") or {}
                    if fleet.get("applied_seq") is not None:
                        h.applied_seq = int(fleet["applied_seq"])
                    sh = (payload or {}).get("shard")
                    h.shard = int(sh["index"]) if sh else None
                else:
                    h.fails += 1
                    h.successes = 0
                    h.last_error = err
                    if h.fails >= cfg.unhealthy_after:
                        h.ready = False
                now_ready = h.ready
                results[h.url] = now_ready
            if not ok:
                self._m_probe_failures.inc()
            if was_ready != now_ready:
                telemetry.event("front_replica_health", url=h.url,
                                ready=str(now_ready), error=str(err))
                logger.warning("front: replica %s -> %s%s", h.url,
                               "READY" if now_ready else "OUT",
                               f" ({err})" if err else "")
                if not now_ready:
                    # a replica just left rotation (crash, health gate,
                    # drain elsewhere): capture the window fleet-wide —
                    # dump the front's own ring and fan the SAME trigger
                    # id out so every live process's bundle correlates
                    self._flight_fleet_dump("replica.unhealthy",
                                            url=h.url, error=str(err))
        self._refresh_gauges()
        self._check_lost_shards()
        return results

    def _note_shard_payload(self, payload) -> Optional[str]:
        """Validate/adopt a probed replica's shard spec.  The newest
        spec VERSION wins fleet-wide (a rebalance rolls out by bumping
        it); a replica whose spec_id disagrees with the adopted
        partition gets an error string back — the probe counts it as a
        failed probe, so it leaves rotation instead of merging margins
        from a different partition."""
        info = (payload or {}).get("shard")
        if info is None:
            return None
        try:
            spec = ShardSpec.from_dict(info)
        except (ValueError, KeyError, TypeError) as e:
            return f"unusable shard spec in /healthz: {e}"
        with self._lock:
            cur = self._shard_spec
            if cur is None or spec.version > cur.version:
                self._shard_spec = cur = spec
        if spec.spec_id() != cur.spec_id():
            return (f"shard spec {spec.spec_id()!r} (v{spec.version}) "
                    f"does not match the fleet partition "
                    f"{cur.spec_id()!r} (v{cur.version})")
        return None

    def shard_coverage(self) -> Optional[Dict[int, int]]:
        """Healthy replicas per shard index (None: fleet unsharded).
        A zero anywhere means that slice of the entity space is DARK —
        scoring degrades per FrontConfig.degraded_policy."""
        with self._lock:
            spec = self._shard_spec
            if spec is None:
                return None
            cov = {k: 0 for k in range(spec.num_shards)}
            for h in self._handles:
                if h.ready and not h.detached and h.shard is not None \
                        and h.shard in cov:
                    cov[h.shard] += 1
        return cov

    def _check_lost_shards(self) -> None:
        """Fire the shard.lost flight trigger on the transition of a
        shard's LAST healthy replica leaving rotation (only for shards
        that had coverage before — startup catch-up is not a loss)."""
        cov = self.shard_coverage()
        if cov is None:
            return
        with self._lock:
            for k, n in cov.items():
                if n > 0:
                    self._seen_shards.add(k)
            lost = {k for k, n in cov.items() if n == 0} & self._seen_shards
            fresh = lost - self._lost_shards
            recovered = self._lost_shards - lost
            self._lost_shards = lost
        for k in sorted(recovered):
            telemetry.event("front_shard_recovered", shard=str(k))
            logger.warning("front: shard %d has healthy replicas again",
                           k)
        for k in sorted(fresh):
            logger.error(
                "front: shard %d LOST its last healthy replica — "
                "requests touching its entities now %s", k,
                "degrade to partial scores"
                if self.config.degraded_policy == "partial"
                else "fail 503")
            self._flight_fleet_dump("shard.lost", shard=str(k))

    def _flight_fleet_dump(self, reason: str, **attrs) -> None:
        """Dump the front's flight ring and broadcast the trigger to
        every other attached, reachable replica (fire-and-forget on the
        pool: a postmortem capture must not block probing/routing)."""
        if not flight.armed():
            return
        trigger_id = flight.new_trigger_id(reason)
        flight.trigger(reason, trigger_id=trigger_id, **attrs)  # photonlint: disable=PH008 -- fans out a caller-validated registered reason
        body = json.dumps({"reason": reason, "trigger_id": trigger_id,
                           "attrs": {k: str(v) for k, v in attrs.items()}
                           }).encode()
        with self._lock:
            handles = [h for h in self._handles if not h.detached]
        for h in handles:
            self._pool.submit(self._flight_dump_one, h, body)

    def _flight_dump_one(self, h: "ReplicaHandle", body: bytes) -> None:
        try:
            self._send(h, "POST", "/flight/dump", body,
                       self.config.probe_timeout_s)
        except Exception:
            pass  # the crashed replica itself is expected to be gone

    def _refresh_gauges(self) -> None:
        with self._lock:
            ready = [h for h in self._handles
                     if h.ready and not h.detached]
            seqs = [h.applied_seq for h in self._handles
                    if not h.detached and h.applied_seq is not None]
        self._m_ready.set(len(ready))
        if seqs:
            self._m_max_lag.set(max(seqs) - min(seqs))
        cov = self.shard_coverage()
        if cov is not None:
            # the MIN healthy-replica count across shards: 0 here is the
            # alertable "part of the entity space is dark" signal
            self._m_shard_coverage.set(float(min(cov.values())))

    def start_probes(self) -> None:
        with self._lock:
            if self._probe_thread is not None:
                return
            thread = threading.Thread(target=self._probe_loop, daemon=True,
                                      name="photon-front-probe")
            self._probe_thread = thread
        thread.start()

    def _probe_loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.probe_once()
            except Exception as e:  # the probe loop must never die
                logger.exception("front probe cycle failed: %s", e)
            self._closed.wait(timeout=self.config.probe_interval_s)

    # -- transport -----------------------------------------------------------

    @staticmethod
    def _send(h: ReplicaHandle, method: str, path: str,
              body: Optional[bytes], timeout: float,
              extra_headers: Optional[Dict[str, str]] = None
              ) -> Tuple[int, bytes]:
        conn = HTTPConnection(h.host, h.port, timeout=timeout)
        try:
            headers = {"Content-Type": "application/json"}
            if body is not None:
                headers["Content-Length"] = str(len(body))
            if extra_headers:
                headers.update(extra_headers)
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    # -- routing -------------------------------------------------------------

    def _pick(self, exclude=(), shard: Optional[int] = None
              ) -> Optional[ReplicaHandle]:
        """Round-robin over ready replicas; `shard=k` restricts the pick
        to replicas that declared ownership of shard k (which also keeps
        the unsharded publisher out of a sharded fleet's scoring
        rotation — it holds the full model but is not a leg)."""
        with self._lock:
            n = len(self._handles)
            for i in range(n):
                h = self._handles[(self._rr + i) % n]
                if h.ready and not h.draining and not h.detached \
                        and h.url not in exclude \
                        and (shard is None or h.shard == shard):
                    self._rr = (self._rr + i + 1) % n
                    h.inflight += 1
                    return h
        return None

    def _release(self, h: ReplicaHandle) -> None:
        with self._lock:
            h.inflight = max(h.inflight - 1, 0)

    def _mark_failure(self, h: ReplicaHandle, err: str) -> None:
        with self._lock:
            h.fails += 1
            h.successes = 0
            h.last_error = err
            if h.fails >= self.config.unhealthy_after:
                h.ready = False

    def route(self, path: str, payload: dict,
              timeout: Optional[float] = None) -> Tuple[int, dict]:
        """Route one scoring request (POST /score | /predict): bounded
        in-flight, failover across ready replicas, hedging on a slow
        attempt.  Returns (HTTP status, decoded payload)."""
        leaf = path.rstrip("/").rsplit("/", 1)[-1]
        if leaf in ("feedback", "swap", "rollback"):
            # model-state changes are NOT idempotent: a hedge or a blind
            # retry after an ambiguous timeout could apply the same
            # feedback batch or swap twice — those routes go through
            # route_publisher(), single attempt, no duplicates ever
            raise ValueError(
                f"{path!r} is a non-idempotent publisher route; the "
                "front never hedges or retries it — use "
                "route_publisher()")
        cfg = self.config
        with self._lock:
            if self._inflight_total >= cfg.max_inflight:
                shed = True
            else:
                shed = False
                self._inflight_total += 1
        if shed:
            self._m_shed.inc()
            raise Overloaded(
                f"front at capacity ({cfg.max_inflight} requests in "
                "flight); retry after the replicas drain")
        self._m_requests.inc()
        body = json.dumps(payload).encode()
        timeout = timeout if timeout is not None else cfg.request_timeout_s
        # ONE logical request = ONE trace: adopt the caller's propagated
        # request id (X-Photon-Trace via the HTTP front or an enclosing
        # server_span) or mint one; every attempt — failover or hedge —
        # carries the same id with this span as the remote parent, so the
        # merged timeline shows the request crossing processes
        request_id = (distributed.current_request_id()
                      or distributed.new_request_id())
        try:
            with distributed.server_span(
                    "front_request", None, request_id=request_id,
                    remote_parent=distributed.current_ref(),
                    path=path) as scope:
                trace_headers = distributed.outbound_headers(
                    scope.request_id, distributed.current_ref())
                with self._lock:
                    sharded = self._shard_spec is not None
                if sharded:
                    return self._route_sharded(path, payload, body,
                                               timeout, trace_headers)
                return self._route_attempts(path, body, timeout,
                                            trace_headers)
        finally:
            with self._lock:
                self._inflight_total -= 1

    def _route_attempts(self, path: str, body: bytes, timeout: float,
                        trace_headers: Optional[Dict[str, str]] = None,
                        shard: Optional[int] = None) -> Tuple[int, dict]:
        cfg = self.config
        tried: set = set()
        pending: Dict[object, ReplicaHandle] = {}
        is_hedge: Dict[object, bool] = {}
        sends = 0
        last_client_error: Optional[Tuple[int, dict]] = None

        def launch(hedge: bool = False) -> bool:
            nonlocal sends
            h = self._pick(exclude=tried, shard=shard)
            if h is None:
                return False
            tried.add(h.url)
            sends += 1
            fut = self._pool.submit(self._send, h, "POST", path, body,
                                    timeout, trace_headers)
            pending[fut] = h
            is_hedge[fut] = hedge
            return True

        def outcome(h: ReplicaHandle, kind: str) -> None:
            self._m_by_replica.inc(replica=h.url, outcome=kind)

        if not launch():
            self._m_errors.inc()
            raise NoReadyReplica(
                "no ready replica to route to (all joining, draining, "
                "failed, or unreachable)")
        hedged = False
        try:
            while pending:
                wait_s = (cfg.hedge_after_s
                          if not hedged and sends < cfg.max_attempts
                          else timeout + 1.0)
                done, _ = wait(list(pending), timeout=wait_s,
                               return_when=FIRST_COMPLETED)
                if not done:
                    # the attempt is slow, not dead: hedge a duplicate at
                    # a different replica, first response wins
                    hedged = True
                    if launch(hedge=True):
                        self._m_hedges.inc()
                        telemetry.event("front_hedged", path=path)
                    continue
                for fut in done:
                    h = pending.pop(fut)
                    self._release(h)
                    try:
                        status, raw = fut.result()
                    except Exception as e:
                        self._mark_failure(h, f"{type(e).__name__}: {e}")
                        self._m_failovers.inc()
                        outcome(h, "error")
                        continue
                    if status >= 500:
                        self._mark_failure(h, f"http {status}")
                        self._m_failovers.inc()
                        outcome(h, "5xx")
                        continue
                    try:
                        decoded = json.loads(raw) if raw else {}
                    except ValueError:
                        decoded = {"error": "undecodable replica response"}
                    if status == 429:
                        # replica backpressure: one chance elsewhere,
                        # else propagate the shed to the client
                        last_client_error = (status, decoded)
                        self._m_retries.inc()
                        outcome(h, "429")
                        continue
                    outcome(h, "ok")
                    if is_hedge.get(fut):
                        # the duplicate beat the original: the hedge
                        # bought this request its latency back
                        self._m_hedge_wins.inc()
                        telemetry.event("front_hedge_won", path=path,
                                        replica=h.url)
                    return status, decoded
                if not pending and sends < cfg.max_attempts:
                    if launch():
                        self._m_retries.inc()
                        continue
            if last_client_error is not None:
                return last_client_error
            self._m_errors.inc()
            raise NoReadyReplica(
                f"request failed on every reachable replica "
                f"({sends} attempt(s): {sorted(tried)})")
        finally:
            for fut, h in pending.items():
                # abandoned hedges: release accounting; the send itself
                # finishes (or times out) on the pool thread
                outcome(h, "abandoned")
                fut.add_done_callback(
                    lambda _f, _h=h: self._release(_h))

    # -- sharded fan-out -------------------------------------------------------

    def _route_leg(self, shard: int, body: bytes, timeout: float,
                   trace_headers: Optional[Dict[str, str]]
                   ) -> Tuple[int, dict]:
        """One shard group's leg of a fan-out request: POST /margins to
        that shard's replicas with the full hedged/failover discipline.
        Transient injected faults at shard.route retry here (bounded);
        a fatal one fails only this leg — the merge then applies the
        degradation policy, so the blast radius stays one shard."""
        last: Optional[Exception] = None
        for _ in range(self.config.max_attempts):
            try:
                faults.fire("shard.route", shard=str(shard))
            except Exception as e:
                if not faults.is_transient(e):
                    raise
                last = e
                self._m_retries.inc()
                continue
            return self._route_attempts("/margins", body, timeout,
                                        trace_headers, shard=shard)
        raise last  # every attempt was consumed by injected transients

    def _collect_legs(self, shard_list, body, timeout, trace_headers,
                      legs_raw: Dict[int, dict],
                      failed: Dict[int, str]) -> None:
        """Fan one round of legs out on the leg pool and sort the
        responses into `legs_raw` / `failed` (per-shard outcome
        counters included)."""
        futs = {k: self._leg_pool.submit(self._route_leg, k, body,
                                         timeout, trace_headers)
                for k in shard_list}
        for k, fut in futs.items():
            try:
                status, decoded = fut.result()
            except Exception as e:
                failed[k] = f"{type(e).__name__}: {e}"
                self._m_shard_requests.inc(shard=str(k), outcome="failed")
                continue
            if status != 200:
                failed[k] = (f"http {status}: "
                             f"{(decoded or {}).get('error', '')}")
                self._m_shard_requests.inc(shard=str(k), outcome="failed")
                continue
            legs_raw[k] = decoded
            self._m_shard_requests.inc(shard=str(k), outcome="ok")

    def _route_sharded(self, path: str, payload: dict, body: bytes,
                       timeout: float,
                       trace_headers: Optional[Dict[str, str]]
                       ) -> Tuple[int, dict]:
        """Route one scoring request across an entity-sharded fleet:
        fan /margins legs to every shard the request's entity ids touch
        (plus one primary leg for the replicated FE/MF coordinates),
        merge the per-coordinate margins bit-identically to a monolithic
        replica, and degrade per `degraded_policy` when a touched shard
        has no healthy replica."""
        with self._lock:
            spec = self._shard_spec
            meta = self._coord_meta
        ids = payload.get("ids") or {}
        cov = self.shard_coverage() or {}
        covered = sorted(k for k, c in cov.items() if c > 0)
        if not covered:
            self._m_errors.inc()
            raise NoReadyReplica(
                "no shard has a healthy replica — the sharded fleet "
                "cannot place any leg")
        if meta is not None:
            needed = set(shards_touched(spec, meta, ids))
        else:
            # the coordinate fold order is unknown until a first leg
            # answers: fan to every shard rather than guess
            needed = set(range(spec.num_shards))
        # the replicated FE/MF margins come from the lowest covered leg
        needed.add(covered[0])
        legs_raw: Dict[int, dict] = {}
        failed: Dict[int, str] = {}
        self._collect_legs(sorted(k for k in needed if cov.get(k, 0) > 0),
                           body, timeout, trace_headers, legs_raw, failed)
        if not legs_raw:
            self._m_errors.inc()
            raise NoReadyReplica(
                f"every shard leg failed: { {k: failed[k] for k in sorted(failed)} }")
        versions = {str(leg.get("model_version"))
                    for leg in legs_raw.values()}
        if len(versions) > 1:
            # legs scored different model versions: merging them would
            # mix tables — this window closes as the swap replicates
            self._m_errors.inc()
            return 503, {"error": "shard legs disagree on model version "
                                  "(fleet mid-swap); retry",
                         "versions": sorted(versions)}
        meta = legs_raw[min(legs_raw)]["coordinates"]
        with self._lock:
            self._coord_meta = meta
        # a swap can change the coordinate set under a stale cached fold
        # order: fan one catch-up round to any newly-needed shards
        extra = sorted(k for k in shards_touched(spec, meta, ids)
                       if k not in needed and cov.get(k, 0) > 0)
        if extra:
            self._collect_legs(extra, body, timeout, trace_headers,
                               legs_raw, failed)
        legs = {k: {name: decode_array(enc)
                    for name, enc in leg["margins"].items()}
                for k, leg in legs_raw.items()}
        fold = ",".join(m["name"] for m in meta)
        merged = last = None
        for _ in range(self.config.max_attempts):
            try:
                faults.fire("shard.merge", coordinate=fold)
                merged = merge_margins(spec, meta, ids, legs, min(legs),
                                       missing_policy="partial")
                break
            except ShardMergeError as e:
                self._m_errors.inc()
                return 503, {"error": f"shard merge failed: {e}"}
            except Exception as e:
                if not faults.is_transient(e):
                    raise
                # a pure host fold over already-collected legs: the
                # retry is bit-exact by construction
                last = e
                self._m_retries.inc()
        if merged is None:
            raise last
        scores = merged["scores"]
        a_leg = legs_raw[min(legs_raw)]
        out: Dict[str, object] = {
            "model_version": a_leg.get("model_version"),
            "sharded": True,
            "shards": sorted(legs_raw),
        }
        if merged["missing_shards"]:
            self._m_shard_degraded.inc()
            if self.config.degraded_policy == "error":
                self._m_errors.inc()
                return 503, {
                    "error": "shard(s) "
                             f"{merged['missing_shards']} have no healthy "
                             "replica and the degradation policy is "
                             "'error'",
                    "missing_shards": merged["missing_shards"],
                    "partial_rows": merged["partial_rows"]}
            # partial: the lost shards' random-effect contributions fold
            # as exactly 0.0 (the unseen-entity default), stamped so the
            # caller KNOWS these rows are partial
            out["degraded"] = True
            out["missing_shards"] = merged["missing_shards"]
            out["partial_rows"] = merged["partial_rows"]
        if path.rstrip("/").rsplit("/", 1)[-1] == "predict":
            # host-side inverse link, identical to the replica's
            # mean_prediction: f64 margins (+ offsets), one eager device
            # mean — no jit, no fresh traces
            from photon_ml_tpu.ops import TASK_LOSSES
            import jax.numpy as jnp
            loss = TASK_LOSSES.get(str(a_leg.get("task_type")))
            if loss is None or getattr(loss, "mean", None) is None:
                self._m_errors.inc()
                return 503, {"error": f"task {a_leg.get('task_type')!r} "
                                      "has no mean function"}
            z = np.asarray(scores, np.float64)
            if payload.get("offsets") is not None:
                z = z + np.asarray(payload["offsets"], np.float64)
            out["predictions"] = np.asarray(loss.mean(
                jnp.asarray(z))).tolist()
        else:
            out["scores"] = np.asarray(scores, np.float64).tolist()
        return 200, out

    def publisher_handle(self) -> Optional[ReplicaHandle]:
        with self._lock:
            for h in self._handles:
                if h.publisher and not h.detached:
                    return h
        return None

    def route_publisher(self, method: str, path: str,
                        payload: Optional[dict] = None,
                        timeout: Optional[float] = None
                        ) -> Tuple[int, dict, Dict[str, str]]:
        """Route a model-state request (feedback/swap/rollback) to the
        publisher replica; returns (status, payload, passthrough
        headers) — Retry-After from the publisher's backpressure rides
        through to the client."""
        h = self.publisher_handle()
        if h is None:
            raise NoReadyReplica("no publisher replica attached")
        body = None if payload is None else json.dumps(payload).encode()
        timeout = (timeout if timeout is not None
                   else self.config.request_timeout_s)
        request_id = (distributed.current_request_id()
                      or distributed.new_request_id())
        conn = HTTPConnection(h.host, h.port, timeout=timeout)
        try:
            with distributed.server_span(
                    "front_request", None, request_id=request_id,
                    remote_parent=distributed.current_ref(),
                    path=path) as scope:
                headers = {"Content-Type": "application/json"}
                if body is not None:
                    headers["Content-Length"] = str(len(body))
                headers.update(distributed.outbound_headers(
                    scope.request_id, distributed.current_ref()))
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            passthrough = {}
            retry_after = resp.getheader("Retry-After")
            if retry_after:
                passthrough["Retry-After"] = retry_after
            try:
                decoded = json.loads(raw) if raw else {}
            except ValueError:
                decoded = {"error": "undecodable replica response"}
            return resp.status, decoded, passthrough
        except (ConnectionError, OSError) as e:
            self._mark_failure(h, f"{type(e).__name__}: {e}")
            self._m_errors.inc()
            raise NoReadyReplica(
                f"publisher {h.url} unreachable: {e}") from e
        finally:
            conn.close()

    # -- drain / audit / status ----------------------------------------------

    def drain(self, url: str, timeout: float = 30.0) -> Dict[str, object]:
        """Take one replica out: stop routing, ask it to drain (its own
        /healthz flips 503), wait for in-flight to finish, detach."""
        url = url.rstrip("/")
        with self._lock:
            handle = next((h for h in self._handles if h.url == url), None)
            if handle is None:
                raise ValueError(f"no attached replica at {url!r}")
            handle.draining = True
            handle.ready = False
        try:
            self._send(handle, "POST", "/fleet/drain", b"{}",
                       self.config.probe_timeout_s)
        except Exception as e:  # drain is best-effort on the replica side
            logger.warning("front: drain request to %s failed: %s", url, e)
        waited = 0.0
        step = 0.05
        while waited < timeout:
            with self._lock:
                if handle.inflight == 0:
                    break
            self._closed.wait(timeout=step)
            waited += step
        with self._lock:
            handle.detached = True
            remaining = handle.inflight
        self._refresh_gauges()
        telemetry.event("front_replica_drained", url=url,
                        inflight_left=str(remaining))
        logger.info("front: replica %s drained and detached "
                    "(waited %.2fs, %d in flight left)", url, waited,
                    remaining)
        return {"url": url, "detached": True, "inflight_left": remaining}

    def attach(self, url: str) -> None:
        """(Re-)attach a replica URL; it enters rotation once probes see
        it ready."""
        url = url.rstrip("/")
        with self._lock:
            for h in self._handles:
                if h.url == url:
                    h.detached = False
                    h.draining = False
                    h.fails = h.successes = 0
                    h.ready = False
                    return
            self._handles.append(ReplicaHandle(url))

    def audit(self) -> Dict[str, object]:
        """Fan /fleet/audit out to every attached replica: the fleet
        convergence check in one call."""
        out: Dict[str, object] = {}
        with self._lock:
            handles = [h for h in self._handles if not h.detached]
        for h in handles:
            try:
                status, raw = self._send(h, "GET", "/fleet/audit", None,
                                         self.config.probe_timeout_s)
                out[h.url] = (json.loads(raw) if status == 200
                              else {"error": f"http {status}"})
            except Exception as e:
                out[h.url] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def status(self) -> Dict[str, object]:
        with self._lock:
            replicas = [h.state() for h in self._handles]
            ready = sum(1 for h in self._handles
                        if h.ready and not h.detached)
            spec = self._shard_spec
        out: Dict[str, object] = {"role": "front",
                                  "ready_replicas": ready,
                                  "replicas": replicas}
        cov = self.shard_coverage()
        if cov is not None:
            out["shards"] = {
                "spec": spec.to_dict(),
                "policy": self.config.degraded_policy,
                "coverage": {str(k): v for k, v in sorted(cov.items())},
                "shards_down": sorted(k for k, v in cov.items()
                                      if v == 0),
            }
        return out

    def prometheus_metrics(self) -> str:
        self._refresh_gauges()
        return prometheus_text(self.registry)

    def metrics_snapshot(self) -> Dict[str, object]:
        self._refresh_gauges()
        return self.registry.snapshot()

    # -- federated metrics ----------------------------------------------------

    def front_snapshot(self) -> Dict[str, object]:
        """The front's OWN instruments as the friendly JSON surface —
        the shape FRONT_SNAPSHOT_PATHS (the metric-surface parity
        contract) declares, path for path."""
        self._refresh_gauges()
        snap = self.registry.snapshot()
        c, g = snap["counters"], snap["gauges"]
        return {
            "requests": c["fleet.front_requests"],
            "failovers": c["fleet.front_failovers"],
            "hedges": c["fleet.front_hedges"],
            "hedge_wins": c["fleet.front_hedge_wins"],
            "retries": c["fleet.front_retries"],
            "shed": c["fleet.front_shed"],
            "errors": c["fleet.front_errors"],
            "probe_failures": c["fleet.front_probe_failures"],
            "scrape_failures": c["fleet.front_scrape_failures"],
            "ready_replicas": g["fleet.front_ready_replicas"],
            "max_lag_seq": g["fleet.front_max_lag_seq"],
            "requests_by_replica": snap["labeled"]["front.requests"],
            "shard_requests": snap["labeled"]["fleet.shard_requests"],
            "shard_coverage": g["fleet.shard_coverage"],
            "shard_degraded": c["fleet.shard_degraded"],
        }

    def _fleet_lag(self) -> Dict[str, object]:
        """Per-replica replication lag derived from the probe payloads:
        the publisher's applied seq IS the log head, so every replica's
        record lag is observable from the front alone."""
        with self._lock:
            head = max((h.applied_seq for h in self._handles
                        if h.publisher and h.applied_seq is not None),
                       default=None)
            per = {h.url: {
                "applied_seq": h.applied_seq,
                "lag_records": (None if h.applied_seq is None
                                or head is None
                                else max(head - h.applied_seq, 0)),
                "ready": int(h.ready and not h.detached),
                "publisher": h.publisher,
            } for h in self._handles if not h.detached}
        return {"publisher_head_seq": head, "replicas": per}

    def _scrape(self, h: ReplicaHandle, path: str):
        """(status, body) from one replica's metrics surface, or None —
        scrape failures are counted, never propagated (a dead replica
        must not take the fleet's metrics page down)."""
        try:
            status, body = self._send(h, "GET", path, None,
                                      self.config.probe_timeout_s)
            if status != 200:
                raise RuntimeError(f"http {status}")
            return body
        except Exception as e:
            self._m_scrape_failures.inc()
            logger.debug("front: metrics scrape of %s%s failed: %s",
                         h.url, path, e)
            return None

    def federated_snapshot(self) -> Dict[str, object]:
        """The fleet's JSON metrics surface: the front's own instruments
        plus every attached replica's /metrics.json, keyed by instance,
        plus the probe-derived per-replica replication lag."""
        with self._lock:
            handles = [h for h in self._handles if not h.detached]
        replicas: Dict[str, object] = {}
        for h in handles:
            body = self._scrape(h, "/metrics.json")
            if body is None:
                replicas[h.url] = {"error": "unreachable"}
                continue
            try:
                replicas[h.url] = json.loads(body)
            except ValueError:
                replicas[h.url] = {"error": "undecodable"}
        return {"front": self.front_snapshot(), "replicas": replicas,
                "fleet": self._fleet_lag()}

    _SERIES_RE = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s(.*)$")

    def _relabel(self, text: str, instance: str, lines: List[str],
                 seen_types: set) -> None:
        """Stamp a scraped exposition page with an instance label so the
        per-replica series coexist on one federated page."""
        for line in text.splitlines():
            if line.startswith("# TYPE"):
                if line not in seen_types:
                    seen_types.add(line)
                    lines.append(line)
                continue
            if line.startswith("#") or not line.strip():
                continue
            m = self._SERIES_RE.match(line)
            if not m:
                continue
            name, _brace, labels, value = m.groups()
            inner = f'instance="{instance}"'
            if labels:
                inner += "," + labels
            lines.append(f"{name}{{{inner}}} {value}")

    def federated_prometheus(self) -> str:
        """The fleet's Prometheus surface (the front's GET /metrics):
        the front's own registry plus every healthy replica's and the
        publisher's exposition, per-replica instance labels, plus the
        probe-derived per-replica lag series."""
        self._refresh_gauges()
        lines: List[str] = []
        seen_types: set = set()
        self._relabel(prometheus_text(self.registry), "front", lines,
                      seen_types)
        with self._lock:
            handles = [h for h in self._handles if not h.detached]
        for h in handles:
            body = self._scrape(h, "/metrics")
            if body is None:
                continue
            self._relabel(body.decode("utf-8", "replace"), h.url, lines,
                          seen_types)
        lag = self._fleet_lag()
        for series in ("photon_fleet_replica_applied_seq",
                       "photon_fleet_replica_lag_records",
                       "photon_fleet_replica_ready"):
            lines.append(f"# TYPE {series} gauge")
        for url, st in sorted(lag["replicas"].items()):
            if st["applied_seq"] is not None:
                lines.append(f'photon_fleet_replica_applied_seq'
                             f'{{instance="{url}"}} {st["applied_seq"]}')
            if st["lag_records"] is not None:
                lines.append(f'photon_fleet_replica_lag_records'
                             f'{{instance="{url}"}} {st["lag_records"]}')
            lines.append(f'photon_fleet_replica_ready'
                         f'{{instance="{url}"}} {st["ready"]}')
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            thread, self._probe_thread = self._probe_thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._leg_pool.shutdown(wait=False, cancel_futures=True)
