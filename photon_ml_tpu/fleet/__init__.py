"""Replicated serving fleet: replication log, replica runtime, front.

One process with one CompiledScorer is not "millions of users" — and it
is a single point of failure.  This package scales the serving tier out:
a durable append-only ReplicationLog carries every model-state change
(full swaps, version-vectored ModelDeltas, delta-aware rollbacks) from
ONE publisher to N replica processes, each of which replays the log
through its own ModelRegistry and converges to bit-identical tables
(audited by version vector + per-table sha256); a health-probing Front
routes scoring traffic across the ready replicas with failover, hedging,
draining and explicit backpressure.  See COMPONENTS.md "Replicated
serving" for the log format and the convergence argument.

Entity-sharded serving (fleet/shards.py) partitions the random-effect
entity space across replicas: a versioned ShardSpec (carried on the log
as a shard_map record) deterministically assigns every entity id to a
shard, sharded replicas hold only their owned slice, and the front fans
scoring out per shard and re-folds margins bit-identically.  See
COMPONENTS.md "Entity-sharded serving".
"""
from photon_ml_tpu.fleet.front import (FRONT_SNAPSHOT_PATHS,  # noqa: F401
                                       Front, FrontConfig,
                                       NoReadyReplica, ReplicaHandle)
from photon_ml_tpu.fleet.replica import (FleetPublisher,  # noqa: F401
                                         Replica, ReplicaConfig,
                                         ReplicaError)
from photon_ml_tpu.fleet.replog import (FeedbackLog,  # noqa: F401
                                        ReplicationLog,
                                        ReplicationLogError, decode_array,
                                        delta_from_record, encode_array,
                                        feedback_from_record,
                                        record_for_event,
                                        record_for_feedback,
                                        record_for_shard_map)
from photon_ml_tpu.fleet.shards import (ShardAssignment,  # noqa: F401
                                        ShardMergeError, ShardSpec,
                                        merge_margins, shards_touched)
