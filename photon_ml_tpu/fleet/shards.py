"""Entity-sharded serving: the shard map and the margin-merge algebra.

Photon ML scaled GAME *training* by sharding per-entity random-effect
sub-problems across executors; this module applies the same partitioning
one level up, to the serving fleet's MEMORY.  A `ShardSpec` deterministically
assigns every entity id to one of `num_shards` shards (sha256 of
``salt:version:id`` — no coordination, no lookup table, stable across
processes and machines), and is versioned + carried on the replication log
(record kind ``shard_map``) so the whole fleet provably agrees on the
partition.  A replica built with a `ShardAssignment` holds only its owned
slice of every random-effect table (fixed-effect and matrix-factorization
coordinates are small and replicated everywhere), filters replicated
deltas/row-state to owned rows, and sizes its tiered-store residency to
the slice — so a 4-shard fleet serves a random-effect space ~4x one
replica's budget.

The merge algebra (`merge_margins`) is what makes fan-out scoring
BIT-IDENTICAL to a monolithic replica: the scorer's compiled program folds
per-coordinate margins with a fixed sequential add chain (FE, then each
RE coordinate in model order, then MF) in the device COMPUTE dtype.
Floating-point addition is commutative but not associative, so a naive
"sum the shard partial scores" merge is NOT exact once a request row
touches two RE coordinates owned by different shards.  Instead every
shard leg returns its PER-COORDINATE margins in the compute dtype; the
front selects, per row and per RE coordinate, the margin computed by the
shard that OWNS that row's entity (the others hold no row for it and
contribute exactly 0.0 — including the sign of a -0.0 the owner
computed), takes FE/MF margins from one designated primary leg, and
re-folds the chain host-side in the same dtype, same order, same
IEEE-754 adds.  Identical operands + identical fold order = identical
bits; the final cast to f64 mirrors the scorer's own output cast.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class ShardMergeError(ValueError):
    """A fan-out merge cannot be completed exactly (missing leg for a
    needed coordinate/owner under the "error" degradation policy, or
    legs that disagree on the coordinate fold order)."""


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """The fleet-wide entity partition: pure function of (salt, version,
    num_shards) — every process that holds the same spec assigns every
    entity id to the same shard, forever."""

    num_shards: int
    salt: str = "photon"
    version: int = 1

    def __post_init__(self):
        if self.num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got "
                             f"{self.num_shards}")

    def shard_of(self, entity_id) -> int:
        """entity id -> owning shard index in [0, num_shards)."""
        key = f"{self.salt}:{self.version}:{entity_id}".encode()
        h = hashlib.sha256(key).digest()
        return int.from_bytes(h[:8], "big") % self.num_shards

    def owned_mask(self, entity_ids: Iterable, shard_index: int
                   ) -> np.ndarray:
        """Boolean mask over `entity_ids`: True where this shard owns."""
        idx = int(shard_index)
        return np.asarray([self.shard_of(e) == idx for e in entity_ids],
                          dtype=bool)

    def spec_id(self) -> str:
        """Short content hash — what the shard_map log record and the
        fleet agreement checks compare."""
        return hashlib.sha256(
            f"{self.num_shards}:{self.salt}:{self.version}"
            .encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"num_shards": self.num_shards, "salt": self.salt,
                "version": self.version, "spec_id": self.spec_id()}

    @classmethod
    def from_dict(cls, d: dict) -> "ShardSpec":
        spec = cls(num_shards=int(d["num_shards"]),
                   salt=str(d.get("salt", "photon")),
                   version=int(d.get("version", 1)))
        want = d.get("spec_id")
        if want is not None and want != spec.spec_id():
            raise ValueError(
                f"shard spec_id mismatch: record says {want!r} but "
                f"{spec!r} hashes to {spec.spec_id()!r} — the fleet is "
                "running incompatible shard-map builds")
        return spec


@dataclasses.dataclass(frozen=True)
class ShardAssignment:
    """One replica's slice of the partition: the fleet-wide spec plus
    this replica's shard index."""

    spec: ShardSpec
    index: int

    def __post_init__(self):
        if not (0 <= self.index < self.spec.num_shards):
            raise ValueError(
                f"shard index {self.index} out of range for "
                f"{self.spec.num_shards} shards")

    def owns(self, entity_id) -> bool:
        return self.spec.shard_of(entity_id) == self.index

    def to_dict(self) -> dict:
        return {"index": self.index, **self.spec.to_dict()}


def shards_touched(spec: ShardSpec,
                   coordinates: Sequence[dict],
                   ids: Dict[str, Sequence]) -> List[int]:
    """The shards a request actually needs for its random-effect
    coordinates: {shard_of(id) for every RE coordinate's entity ids}.
    `coordinates` is the scorer's coordinate_meta() (ordered dicts with
    "kind" and, for RE entries, "entity_type")."""
    touched = set()
    for meta in coordinates:
        if meta.get("kind") != "random":
            continue
        for e in np.asarray(ids.get(meta["entity_type"], ())).tolist():
            touched.add(spec.shard_of(e))
    return sorted(touched)


def merge_margins(spec: ShardSpec,
                  coordinates: Sequence[dict],
                  ids: Dict[str, Sequence],
                  legs: Dict[int, Dict[str, np.ndarray]],
                  primary: int,
                  *,
                  missing_policy: str = "error",
                  ) -> Dict[str, object]:
    """Fold per-shard margin legs back into total scores, bit-identically
    to the monolithic scorer's device add chain.

    `legs` maps shard index -> {coordinate name -> [n] margins in the
    scorer's compute dtype — CompiledScorer.score_margins output} (each
    leg scored the SAME request).  `primary` names the leg FE/MF margins
    are taken from (every shard replicates those coordinates in full, so
    any healthy leg is exact; the front passes its lowest-index healthy
    shard).  For each RE coordinate the per-row margin is taken from the
    row's OWNING shard's leg — the bit-exact monolithic value, since the
    owner's partial table holds the identical row and the identical
    compiled dot program produced the margin.

    Rows whose owner leg is absent (that shard is down): under
    ``missing_policy="error"`` raise `ShardMergeError`; under
    ``"partial"`` the missing contribution folds as exactly 0.0 — the
    same value an UNSEEN entity contributes — and the row is reported in
    ``partial_rows``.  Returns {"scores": [n] f64, "partial_rows":
    sorted row indices, "missing_shards": sorted shard indices}.
    """
    if primary not in legs:
        raise ShardMergeError(
            f"primary leg (shard {primary}) is missing from the merge")
    if missing_policy not in ("error", "partial"):
        raise ValueError(f"unknown missing_policy {missing_policy!r}")
    prim = legs[primary]
    n = dtype = None
    for name, m in prim.items():
        m = np.asarray(m)
        if n is None:
            n, dtype = int(m.shape[0]), m.dtype
        elif int(m.shape[0]) != n:
            raise ShardMergeError(
                f"primary leg margin {name!r} has {m.shape[0]} rows, "
                f"expected {n}")
    if n is None:
        raise ShardMergeError("primary leg carries no margins")
    scores = np.zeros(n, dtype)
    partial_rows: set = set()
    missing_shards: set = set()
    for meta in coordinates:
        name = meta["name"]
        if name not in prim:
            raise ShardMergeError(
                f"primary leg is missing margins for coordinate {name!r}")
        if meta.get("kind") != "random":
            contrib = np.asarray(prim[name], dtype)
        else:
            owners = [spec.shard_of(e) for e in
                      np.asarray(ids[meta["entity_type"]]).tolist()]
            if len(owners) != n:
                raise ShardMergeError(
                    f"ids[{meta['entity_type']!r}] has {len(owners)} "
                    f"rows, margins have {n}")
            contrib = np.zeros(n, dtype)
            for i, owner in enumerate(owners):
                leg = legs.get(owner)
                if leg is None:
                    missing_shards.add(owner)
                    if missing_policy == "error":
                        raise ShardMergeError(
                            f"shard {owner} (owner of row {i}'s "
                            f"{meta['entity_type']!r} entity) has no "
                            "healthy replica and the degradation policy "
                            "is 'error'")
                    partial_rows.add(i)
                    continue  # folds as exactly 0.0, like an unseen id
                contrib[i] = np.asarray(leg[name])[i]
        # the same sequential per-coordinate add chain the compiled
        # scorer folds on device, in the same compute dtype: identical
        # operands, identical order, identical bits
        scores = scores + contrib
    return {"scores": np.asarray(scores, np.float64),
            "partial_rows": sorted(partial_rows),
            "missing_shards": sorted(missing_shards)}
