"""Durable append-only replication log: the fleet's model-state backbone.

Every model-state change of the publisher's ModelRegistry — full-model
swap, row-level ModelDelta, delta-aware rollback, full-model rollback —
lands here as ONE checksummed JSON record in an fsynced segment file, in
the exact mutation order (the registry's publish-hook tickets).  Replicas
tail the log and replay records through their own registries, converging
to BIT-IDENTICAL tables: arrays are encoded as base64 of the raw device
bytes (dtype + shape + buffer), so a float64 row survives the round trip
bit-for-bit — no decimal re-parsing in the convergence path.

Durability discipline (utils/durable.py, photonlint PH005): segment
appends go through `durable.append_text` (write + flush + fsync); appends
are not atomic the way replace-writes are, so every record carries a
sha256 over its canonical encoding and a TORN TAIL — the half-record a
crash mid-append leaves — is detected and ignored on read (and truncated
on the publisher's next open).  Mid-file corruption is NOT a torn tail
and raises: that log is damaged, not merely interrupted.

Compaction folds acked records (everything at or below the minimum
applied seq across live replicas) into a snapshot: the net row state vs a
base model directory, written atomically to `snapshot.json`, after which
fully-covered segments are deleted.  A joining replica bootstraps from
the snapshot and replays only the tail.

Single-writer contract: exactly one publisher appends (the fleet's
FleetPublisher serializes registry tickets through `append`).  A second
concurrent appender is an error, not a silent interleave.

Fault sites (utils.faults.SITES): `replog.append` fires before each
record write (transient -> the publisher's retry-with-backoff absorbs
it), `replog.read` before each tail read (transient -> the replica's
poll-loop retry absorbs it).

Feedback lane (`FeedbackLog`): labeled-observation batches admitted by
the online updater land in sibling `feedback-*.seg` segments with the
SAME sha256/torn-tail/fsync discipline, so the refit compactor
(photon_ml_tpu/refit/) replays a complete training source from the
fleet's own exhaust.  Compaction on either lane is bounded by registered
consumers (`register_consumer`): folding past the newest seq a refit
compactor checkpoint still needs would strand the compactor exactly the
way folding past a replica's applied seq strands the replica.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.utils import durable, faults, locktrace


class ReplicationLogError(RuntimeError):
    """Structural log failure (corruption mid-file, concurrent appenders,
    compacted-away history) — never a torn tail, which is recovered."""


#: records per segment file before rotation
SEGMENT_RECORDS = 1024

_SEGMENT_PREFIX = "segment-"
_SEGMENT_SUFFIX = ".log"
_SNAPSHOT_NAME = "snapshot.json"


# -- bit-exact array transport ------------------------------------------------

def encode_array(a) -> Dict[str, object]:
    """numpy array -> {dtype, shape, b64 raw bytes}: exact byte transport
    (JSON floats would survive repr round-trips too, but raw bytes make
    bit-identity a property of the ENCODING, not of the parser)."""
    a = np.ascontiguousarray(np.asarray(a))
    return {"dtype": a.dtype.str, "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(d: Dict[str, object]) -> np.ndarray:
    a = np.frombuffer(base64.b64decode(d["b64"]),
                      dtype=np.dtype(str(d["dtype"])))
    return a.reshape([int(s) for s in d["shape"]]).copy()


def _canonical(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _line_for(envelope: dict) -> str:
    sha = hashlib.sha256(_canonical(envelope).encode()).hexdigest()[:16]
    return _canonical({**envelope, "sha": sha}) + "\n"


def _parse_line(line: str) -> Optional[dict]:
    """One segment line -> envelope dict, or None when the line is torn
    (incomplete JSON / missing or mismatched checksum)."""
    line = line.strip()
    if not line:
        return None
    try:
        env = json.loads(line)
    except ValueError:
        return None
    sha = env.pop("sha", None)
    if sha != hashlib.sha256(_canonical(env).encode()).hexdigest()[:16]:
        return None
    return env


class ReplicationLog:
    #: segment naming, overridable by sibling lanes (FeedbackLog)
    _PREFIX = _SEGMENT_PREFIX
    _SUFFIX = _SEGMENT_SUFFIX
    _SNAP = _SNAPSHOT_NAME

    def __init__(self, log_dir: str, segment_records: int = SEGMENT_RECORDS):
        self.log_dir = str(log_dir)
        self.segment_records = int(segment_records)
        os.makedirs(self.log_dir, exist_ok=True)
        self._lock = locktrace.tracked(threading.Lock(),
                                       "ReplicationLog._lock")
        self._appending = False                 # photonlint: guarded-by=_lock
        self._head_seq: Optional[int] = None    # photonlint: guarded-by=_lock
        # compaction consumers: name -> checkpoint_fn() returning the
        # newest seq that consumer has durably absorbed.  compact() never
        # folds past the minimum — a refit compactor's unread tail is as
        # load-bearing as a replica's unapplied tail.
        self._consumers: Dict[str, Callable[[], int]] = {}

    # -- segment bookkeeping -------------------------------------------------

    def _segments(self) -> List[str]:
        try:
            names = os.listdir(self.log_dir)
        except FileNotFoundError:
            return []
        return sorted(n for n in names
                      if n.startswith(self._PREFIX)
                      and n.endswith(self._SUFFIX))

    @classmethod
    def _first_seq_of(cls, name: str) -> int:
        return int(name[len(cls._PREFIX):-len(cls._SUFFIX)])

    def _segment_path(self, first_seq: int) -> str:
        return os.path.join(
            self.log_dir,
            f"{self._PREFIX}{first_seq:010d}{self._SUFFIX}")

    def _scan_segment(self, name: str) -> List[dict]:
        """Parse one segment; a torn LAST line is dropped, a bad record
        anywhere else is corruption and raises."""
        path = os.path.join(self.log_dir, name)
        with open(path) as f:
            lines = f.readlines()
        out: List[dict] = []
        for i, line in enumerate(lines):
            env = _parse_line(line)
            if env is None:
                if i == len(lines) - 1:
                    break  # torn tail: the crash interrupted this append
                raise ReplicationLogError(
                    f"corrupt record at {name}:{i + 1} (not the final "
                    "line, so this is damage, not a torn append)")
            out.append(env)
        return out

    def head_seq(self) -> int:
        """Newest durable record's log seq (0 = empty log; snapshot-only
        logs report the snapshot's upto_seq)."""
        with self._lock:
            if self._head_seq is not None:
                return self._head_seq
        head = 0
        snap = self.latest_snapshot()
        if snap is not None:
            head = int(snap["upto_seq"])
        for name in reversed(self._segments()):
            records = self._scan_segment(name)
            if records:
                head = max(head, int(records[-1]["log_seq"]))
                break
        with self._lock:
            self._head_seq = head
        return head

    # -- append (single writer) ----------------------------------------------

    def append(self, record: dict) -> int:
        """Durably append one record; returns its log seq.  Single-writer:
        the publisher serializes calls (registry ticket order), and a
        second concurrent appender raises instead of interleaving.  The
        fsync happens OUTSIDE the lock — ordering is safe because only
        the one legitimate appender ever reaches the write."""
        with self._lock:
            if self._appending:
                raise ReplicationLogError(
                    "concurrent append — the replication log is "
                    "single-writer (one FleetPublisher per log)")
            self._appending = True
        try:
            head = self.head_seq()
            seq = head + 1
            faults.fire("replog.append", kind=str(record.get("kind")))
            segments = self._segments()
            if segments:
                last = segments[-1]
                path = os.path.join(self.log_dir, last)
                if self._count_records(path) >= self.segment_records:
                    path = self._segment_path(seq)
            else:
                path = self._segment_path(seq)
            envelope = {"log_seq": seq, "t": time.time(), "record": record}
            durable.append_text(path, _line_for(envelope))
            with self._lock:
                self._head_seq = seq
            return seq
        finally:
            with self._lock:
                self._appending = False

    def _count_records(self, path: str) -> int:
        with open(path) as f:
            return sum(1 for line in f if line.strip())

    def recover(self) -> int:
        """Publisher-side open: truncate a torn tail left by a crash
        mid-append so future appends extend a clean segment.  Returns the
        number of bytes dropped (0 = clean)."""
        segments = self._segments()
        if not segments:
            return 0
        path = os.path.join(self.log_dir, segments[-1])
        good_end = 0
        with open(path, "rb") as f:
            for raw in f:
                if _parse_line(raw.decode("utf-8", "replace")) is None:
                    break
                good_end += len(raw)
        size = os.path.getsize(path)
        if good_end < size:
            with open(path, "rb+") as f:
                f.truncate(good_end)
            durable.fsync_file(path)
            with self._lock:
                self._head_seq = None  # recompute past the truncation
            return size - good_end
        return 0

    # -- read ----------------------------------------------------------------

    def read(self, after_seq: int) -> List[dict]:
        """All durable records with log_seq > after_seq, in order.  Raises
        ReplicationLogError when that history was compacted away (the
        caller must bootstrap from `latest_snapshot()` instead)."""
        faults.fire("replog.read", segment=str(int(after_seq)))
        out: List[dict] = []
        expected = None
        for name in self._segments():
            first = self._first_seq_of(name)
            records = self._scan_segment(name)
            if records and int(records[-1]["log_seq"]) <= after_seq:
                continue
            for env in records:
                seq = int(env["log_seq"])
                if seq <= after_seq:
                    continue
                if expected is None:
                    if seq != after_seq + 1:
                        snap = self.latest_snapshot()
                        if snap is not None and \
                                int(snap["upto_seq"]) >= after_seq:
                            raise ReplicationLogError(
                                f"records after seq {after_seq} were "
                                "compacted away — bootstrap from the "
                                "snapshot (upto_seq "
                                f"{snap['upto_seq']}) and replay from "
                                "there")
                        raise ReplicationLogError(
                            f"log gap: expected seq {after_seq + 1}, "
                            f"found {seq} (segment {name})")
                elif seq != expected:
                    raise ReplicationLogError(
                        f"log gap: expected seq {expected}, found {seq} "
                        f"(segment {name})")
                expected = seq + 1
                out.append(env)
        return out

    # -- snapshot + compaction ----------------------------------------------

    def latest_snapshot(self) -> Optional[dict]:
        path = os.path.join(self.log_dir, self._SNAP)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    # -- bounded retention (compaction consumers) -----------------------------

    def register_consumer(self, name: str,
                          checkpoint_fn: Callable[[], int]) -> None:
        """Register a compaction consumer (e.g. the refit compactor):
        `checkpoint_fn()` returns the newest log seq that consumer has
        durably absorbed, and `compact()` refuses to fold past the
        minimum across all registered consumers — records a checkpoint
        still needs stay readable."""
        with self._lock:
            self._consumers[str(name)] = checkpoint_fn

    def unregister_consumer(self, name: str) -> None:
        with self._lock:
            self._consumers.pop(str(name), None)

    def _retention_clamp(self, upto_seq: int) -> int:
        with self._lock:
            fns = dict(self._consumers)
        for fn in fns.values():
            upto_seq = min(upto_seq, int(fn()))
        return upto_seq

    def _note_compacted(self, *, upto_seq: int, requested_seq: int,
                        folded: int, segments_deleted: int) -> None:
        telemetry.event("replog.compacted", lane=type(self).__name__,
                        upto_seq=upto_seq, requested_seq=requested_seq,
                        folded=folded, segments_deleted=segments_deleted,
                        clamped=upto_seq < requested_seq)
        telemetry.counter("replog.compacted").inc()

    def _drop_covered_segments(self, upto_seq: int) -> int:
        """Delete segments whose every record is <= upto_seq; returns the
        number removed."""
        segments = self._segments()
        dropped = 0
        for i, name in enumerate(segments):
            nxt = (self._first_seq_of(segments[i + 1])
                   if i + 1 < len(segments) else None)
            if nxt is not None and nxt - 1 <= upto_seq:
                os.remove(os.path.join(self.log_dir, name))
                dropped += 1
            elif nxt is None:
                records = self._scan_segment(name)
                if records and int(records[-1]["log_seq"]) <= upto_seq:
                    os.remove(os.path.join(self.log_dir, name))
                    dropped += 1
        durable.fsync_dir(self.log_dir)
        return dropped

    def compact(self, upto_seq: int) -> Optional[dict]:
        """Fold every record with log_seq <= upto_seq into a snapshot —
        the net row state per coordinate vs the base model directory —
        then delete segments wholly covered by it.  `upto_seq` must be
        the minimum APPLIED seq across live replicas (folding records a
        replica has not applied would strand it), and is additionally
        clamped to the minimum registered consumer checkpoint (a refit
        compactor's unread tail is never folded away).  Returns the
        snapshot (None when there is nothing to fold)."""
        requested = int(upto_seq)
        upto_seq = self._retention_clamp(requested)
        snap = self.latest_snapshot()
        if upto_seq <= (int(snap["upto_seq"]) if snap else 0):
            return snap
        state = _FoldState.from_snapshot(snap)
        folded = 0
        for env in self.read(state.seq):
            if int(env["log_seq"]) > upto_seq:
                break
            state.fold(env)
            folded += 1
        if folded == 0:
            return snap
        new_snap = state.to_snapshot()
        durable.atomic_write_json(
            os.path.join(self.log_dir, self._SNAP), new_snap)
        dropped = self._drop_covered_segments(upto_seq)
        self._note_compacted(upto_seq=upto_seq, requested_seq=requested,
                             folded=folded, segments_deleted=dropped)
        return new_snap

    # -- lane accounting (fleet.log_records / fleet.log_bytes gauges) ---------

    def live_records(self) -> int:
        """Records currently held in durable segments (excludes history
        folded into the snapshot)."""
        return sum(self._count_records(os.path.join(self.log_dir, name))
                   for name in self._segments())

    def live_bytes(self) -> int:
        """Bytes currently held in durable segments."""
        total = 0
        for name in self._segments():
            try:
                total += os.path.getsize(os.path.join(self.log_dir, name))
            except FileNotFoundError:
                pass
        return total


class _FoldState:
    """Compaction simulator: replays records host-side with the same
    semantics a replica's registry applies them with, keeping the net
    row value per (coordinate, row) — last write wins, rollbacks restore
    — plus the previous version's as-last-served rows so a full-model
    rollback folds correctly."""

    def __init__(self):
        self.seq = 0
        self.model_dir: Optional[str] = None
        self.version: Optional[str] = None
        self.delta_seq = 0
        self.rows: Dict[str, Dict[int, np.ndarray]] = {}
        self.previous = None  # (model_dir, version, delta_seq, rows)
        # entity-shard partition (fleet/shards.py ShardSpec.to_dict):
        # fleet topology, not model state — survives swaps/rollbacks
        self.shard_map: Optional[dict] = None

    @classmethod
    def from_snapshot(cls, snap: Optional[dict]) -> "_FoldState":
        st = cls()
        if snap is None:
            return st
        st.seq = int(snap["upto_seq"])
        st.model_dir = snap["model_dir"]
        st.version = snap["version"]
        st.delta_seq = int(snap["delta_seq"])
        for lane, enc in snap.get("restored", {}).items():
            rows = decode_array(enc["rows"])
            values = decode_array(enc["values"])
            st.rows[lane] = {int(r): v for r, v in zip(rows, values)}
        st.shard_map = snap.get("shard_map")
        return st

    def fold(self, env: dict) -> None:
        rec = env["record"]
        kind = rec["kind"]
        if kind == "swap":
            if not rec.get("source_dir"):
                raise ReplicationLogError(
                    f"cannot compact across the in-memory swap at seq "
                    f"{env['log_seq']} (version {rec['version']!r}): a "
                    "snapshot must name a loadable base model directory")
            self.previous = (self.model_dir, self.version, self.delta_seq,
                             {lane: dict(rows)
                              for lane, rows in self.rows.items()})
            self.model_dir = rec["source_dir"]
            self.version = rec["version"]
            self.delta_seq = 0
            self.rows = {}
        elif kind == "delta":
            for lane, enc in rec["coordinates"].items():
                lane_rows = self.rows.setdefault(lane, {})
                for r, v in zip(decode_array(enc["rows"]),
                                decode_array(enc["values"])):
                    lane_rows[int(r)] = v
            self.delta_seq = int(rec["delta_seq"])
        elif kind == "delta_rollback":
            for lane, enc in rec["restored"].items():
                lane_rows = self.rows.setdefault(lane, {})
                for r, v in zip(decode_array(enc["rows"]),
                                decode_array(enc["values"])):
                    lane_rows[int(r)] = v
            self.delta_seq = int(rec["to_delta_seq"])
        elif kind == "shard_map":
            # versioned entity partition announcement: last one wins (a
            # rebalance appends a new record; replicas built for another
            # spec refuse it at apply time, not here)
            self.shard_map = dict(rec["spec"])
        elif kind == "rollback":
            if self.previous is None or self.previous[0] is None:
                raise ReplicationLogError(
                    f"cannot compact across the full-model rollback at "
                    f"seq {env['log_seq']}: the previous version's base "
                    "directory is unknown")
            (self.model_dir, self.version, self.delta_seq,
             self.rows) = self.previous
            self.previous = None
        else:
            raise ReplicationLogError(
                f"unknown record kind {kind!r} at seq {env['log_seq']} — "
                "refusing to fold records this build does not understand")
        self.seq = int(env["log_seq"])

    def to_snapshot(self) -> dict:
        if self.model_dir is None:
            raise ReplicationLogError(
                "nothing to snapshot: no swap record named a base model "
                "directory")
        restored = {}
        for lane, lane_rows in self.rows.items():
            if not lane_rows:
                continue
            idx = sorted(lane_rows)
            restored[lane] = {
                "rows": encode_array(np.asarray(idx, np.int64)),
                "values": encode_array(np.stack(
                    [lane_rows[r] for r in idx]))}
        out = {"format_version": 1, "upto_seq": self.seq,
               "model_dir": self.model_dir, "version": self.version,
               "delta_seq": self.delta_seq, "restored": restored,
               "created_at": time.time()}
        if self.shard_map is not None:
            out["shard_map"] = dict(self.shard_map)
        return out


# -- record constructors (the publisher's event -> record mapping) -----------

def record_for_event(event: dict) -> dict:
    """A ModelRegistry publish-hook event -> its log record."""
    kind = event["kind"]
    if kind == "swap":
        return {"kind": "swap", "version": event["version"],
                "previous_version": event.get("previous_version"),
                "source_dir": event.get("source_dir")}
    if kind == "delta":
        delta = event["delta"]
        rec = {"kind": "delta", "version": event["version"],
               "base_version": delta.base_version,
               "delta_seq": int(delta.seq),
               "created_at": float(delta.created_at),
               "coordinates": {
                   lane: {"rows": encode_array(cd.rows),
                          "values": encode_array(cd.values),
                          "prior": encode_array(cd.prior)}
                   for lane, cd in delta.coordinates.items()}}
        if getattr(delta, "trace", None):
            # cross-process trace metadata (request ids + publisher span
            # ref + oldest intake wall time): replicas attach it to their
            # apply spans so `cli.trace merge` stitches the feedback ->
            # delta -> apply flow into one tree
            rec["trace"] = dict(delta.trace)
        return rec
    if kind == "delta_rollback":
        return {"kind": "delta_rollback", "version": event["version"],
                "to_delta_seq": int(event["to_delta_seq"]),
                "restored": {
                    lane: {"rows": encode_array(rows),
                           "values": encode_array(values)}
                    for lane, (rows, values) in event["restored"].items()}}
    if kind == "rollback":
        return {"kind": "rollback", "version": event["version"],
                "previous_version": event.get("previous_version"),
                "degraded": bool(event.get("degraded", False))}
    raise ReplicationLogError(f"unknown publish event kind {kind!r}")


def record_for_shard_map(spec) -> dict:
    """A fleet shard-map announcement (fleet/shards.py ShardSpec) -> its
    log record.  The publisher appends one when it anchors a sharded
    fleet's log (and after any rebalance bumps the spec version), so the
    partition every replica filters by is itself replicated, versioned,
    and audited like model state."""
    return {"kind": "shard_map", "spec": spec.to_dict()}


def delta_from_record(rec: dict):
    """A "delta" log record -> the ModelDelta a replica's registry
    applies (bit-exact arrays)."""
    from photon_ml_tpu.online.delta import CoordinateDelta, ModelDelta
    return ModelDelta(
        base_version=rec["base_version"], seq=int(rec["delta_seq"]),
        coordinates={
            lane: CoordinateDelta(rows=decode_array(enc["rows"]),
                                  values=decode_array(enc["values"]),
                                  prior=decode_array(enc["prior"]))
            for lane, enc in rec["coordinates"].items()},
        created_at=float(rec.get("created_at", 0.0)))


# -- feedback lane (labeled-observation exhaust) ------------------------------

_FEEDBACK_PREFIX = "feedback-"
_FEEDBACK_SUFFIX = ".seg"
_FEEDBACK_SNAPSHOT_NAME = "feedback-snapshot.json"


class FeedbackLog(ReplicationLog):
    """Sibling durable lane for admitted labeled feedback batches: the
    refit compactor's complete labeled-observation source.

    Same single-writer, sha256-per-record, torn-tail-truncating,
    fsynced-segment discipline as the model-state log, with `feedback-`
    `.seg` segment naming so one directory can host both lanes.  There is
    no row-state fold here — the refit compactor's sealed chunk files ARE
    this lane's compacted form — so `compact(upto_seq)` prunes covered
    segments and records the pruned horizon in a marker snapshot
    (`feedback-snapshot.json`, so `head_seq()` and compacted-history
    reads keep the base class's semantics).  Retention is bounded by
    registered consumers exactly like the model lane."""

    _PREFIX = _FEEDBACK_PREFIX
    _SUFFIX = _FEEDBACK_SUFFIX
    _SNAP = _FEEDBACK_SNAPSHOT_NAME

    def compact(self, upto_seq: int) -> Optional[dict]:
        """Prune segments wholly covered by `upto_seq` (clamped to the
        minimum registered consumer checkpoint) and persist the pruned
        horizon.  Returns the marker snapshot."""
        requested = int(upto_seq)
        upto_seq = self._retention_clamp(requested)
        snap = self.latest_snapshot()
        prev = int(snap["upto_seq"]) if snap else 0
        if upto_seq <= prev:
            return snap
        covered = sum(
            1 for env in self.read(prev)
            if int(env["log_seq"]) <= upto_seq)
        if covered == 0:
            return snap
        new_snap = {"format_version": 1, "kind": "feedback",
                    "upto_seq": upto_seq, "created_at": time.time()}
        durable.atomic_write_json(
            os.path.join(self.log_dir, self._SNAP), new_snap)
        dropped = self._drop_covered_segments(upto_seq)
        self._note_compacted(upto_seq=upto_seq, requested_seq=requested,
                             folded=covered, segments_deleted=dropped)
        with self._lock:
            self._head_seq = None  # recompute against the new horizon
        return new_snap


def record_for_feedback(features: Dict[str, np.ndarray],
                        ids: Dict[str, np.ndarray],
                        labels: np.ndarray,
                        weights: Optional[np.ndarray] = None,
                        offsets: Optional[np.ndarray] = None,
                        *,
                        event_ids: Optional[List[str]] = None,
                        trace_id: Optional[str] = None,
                        wall_s: Optional[float] = None) -> dict:
    """An admitted feedback batch -> its durable log record (bit-exact
    float transport; raw entity ids as strings)."""
    labels = np.asarray(labels, np.float64)
    n = int(labels.shape[0])
    weights = (np.ones(n) if weights is None
               else np.asarray(weights, np.float64))
    offsets = (np.zeros(n) if offsets is None
               else np.asarray(offsets, np.float64))
    rec = {"kind": "feedback", "rows": n,
           "features": {s: encode_array(np.asarray(a, np.float64))
                        for s, a in features.items()},
           "ids": {t: [str(v) for v in np.asarray(a).tolist()]
                   for t, a in ids.items()},
           "labels": encode_array(labels),
           "weights": encode_array(weights),
           "offsets": encode_array(offsets),
           "wall_s": float(time.time() if wall_s is None else wall_s)}
    if event_ids is not None:
        rec["event_ids"] = [None if e is None else str(e)
                            for e in event_ids]
    if trace_id:
        rec["trace_id"] = str(trace_id)
    return rec


def feedback_from_record(rec: dict) -> dict:
    """A "feedback" log record -> host arrays (the compactor's input):
    {features: {shard: [n,d] f64}, ids: {type: [n] object}, labels,
    weights, offsets, wall_s, event_ids, trace_id}."""
    if rec.get("kind") != "feedback":
        raise ReplicationLogError(
            f"not a feedback record: kind={rec.get('kind')!r}")
    return {
        "features": {s: decode_array(enc)
                     for s, enc in rec["features"].items()},
        "ids": {t: np.asarray(v, dtype=object)
                for t, v in rec["ids"].items()},
        "labels": decode_array(rec["labels"]),
        "weights": decode_array(rec["weights"]),
        "offsets": decode_array(rec["offsets"]),
        "wall_s": float(rec.get("wall_s", 0.0)),
        "event_ids": rec.get("event_ids"),
        "trace_id": rec.get("trace_id"),
    }
