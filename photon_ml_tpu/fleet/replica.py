"""Replica runtime + publisher bridge: N scorers kept bit-identical.

`FleetPublisher` attaches to ONE ScoringService's ModelRegistry (the
publisher — typically the replica running the OnlineUpdater) and turns
its ordered publish-hook events into replication-log records: the
registry assigns a ticket per mutation UNDER its lock, the publisher
reorders racing hook invocations by ticket, and a single-flusher loop
appends to the log with transient-retry backoff — so the log is always
a prefix-exact serialization of the publisher's model state.

`Replica` wraps a follower ScoringService.  Lifecycle:

  join      load the latest snapshot (if the tail was compacted away),
            replay the log tail through the local registry, pre-compile
            the delta scatter programs (`CompiledScorer.warmup_delta`) —
            only then report ready (/healthz stops returning 503)
  apply     the poll loop tails the log; each record applies through the
            SAME registry primitives the publisher mutated with
            (apply_delta / replay_row_state / load / rollback), so the
            tables converge bit-identically (audited by version vector +
            per-table sha256, GET /fleet/audit)
  crash     the applied seq is durably recorded (state_dir/applied.json,
            atomic write+fsync) TOGETHER with the replica's folded row
            state (base model dir + net changed rows — the same fold the
            log's compaction computes), because a restarted process
            rebuilds its tables from the base model: progress without
            the matching table state would silently skip history.  Every
            record replay is additionally IDEMPOTENT (version-vector
            guards skip what already landed), so a SIGKILLed replica
            resumes from its durable seq and converges bit-identically.
            A state dir that predates a full-model rollback the restart
            cannot replay (the previous scorer is gone) fails LOUDLY
            with a rejoin-fresh hint rather than serving diverged tables
  drain     stop applying + flip /healthz to 503; the front stops
            routing, in-flight requests finish, then the process detaches

Containment mirrors chunk staging (utils/faults.py sites `replica.apply`
and `replog.read`): transient failures retry with jittered exponential
backoff; fatal ones mark the replica failed — loudly visible on
/healthz, never a silently stale scorer.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
import time
from typing import Dict, Optional

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import flight
from photon_ml_tpu.telemetry.timings import clock

from photon_ml_tpu.fleet.replog import (ReplicationLog, ReplicationLogError,
                                        _FoldState, decode_array,
                                        delta_from_record, record_for_event,
                                        record_for_shard_map)
from photon_ml_tpu.utils import durable, faults, locktrace

logger = logging.getLogger("photon_ml_tpu")

_APPLIED_NAME = "applied.json"


class ReplicaError(RuntimeError):
    """The replica cannot continue applying (fatal apply failure, record
    stream divergence) — surfaced on /healthz as failed."""


@dataclasses.dataclass(frozen=True)
class ReplicaConfig:
    """Knobs of the replica runtime (cli.serve --replica maps 1:1)."""

    poll_interval_s: float = 0.05   # log tail poll period
    max_attempts: int = 3           # transient read/apply retries
    backoff_s: float = 0.02         # base of the jittered exp backoff
    warm_delta_rows: int = 64       # scatter programs pre-compiled up to
                                    # this pow-2 delta row count
    ack_every: int = 8              # durable applied-seq write cadence
                                    # (always also written at batch end)


class FleetPublisher:
    """Bridges a publisher registry's ordered mutation events into the
    replication log.  Register BEFORE the updater starts and before any
    swap/rollback traffic: events are ordered by registry ticket, and the
    publisher's base ticket is captured at attach."""

    def __init__(self, service, log: ReplicationLog,
                 model_dir: Optional[str] = None, max_attempts: int = 3,
                 backoff_s: float = 0.02, shard_spec=None):
        """`shard_spec` (a fleet.shards.ShardSpec) declares the fleet's
        entity partition: anchoring an empty log appends a `shard_map`
        record BEFORE the base swap, so every joining replica learns (and
        validates against) the partition it must filter by.  The
        publisher itself stays UNSHARDED — it holds the full model,
        solves online deltas against it, and the per-replica shard
        filtering happens at apply time on the followers."""
        self.service = service
        self.log = log
        self.shard_spec = shard_spec
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self._lock = locktrace.tracked(threading.Lock(),
                                       "FleetPublisher._lock")
        self._buffer: Dict[int, dict] = {}      # photonlint: guarded-by=_lock
        self._flushing = False                  # photonlint: guarded-by=_lock
        self._failed: Optional[str] = None      # photonlint: guarded-by=_lock
        self._appended = 0                      # photonlint: guarded-by=_lock
        self._jitter = random.Random(0xF1EE7)
        dropped = log.recover()
        if dropped:
            logger.warning("replication log: truncated %d torn tail "
                           "byte(s) left by a previous crash", dropped)
        self._next = service.registry.add_publish_hook(self._on_event)
        # anchor an empty log: the shard map (when the fleet is
        # entity-sharded) and then the CURRENT model as its first swap
        # record, so replicas that joined with a different --model-dir
        # still converge onto the publisher's base model
        if log.head_seq() == 0:
            if shard_spec is not None:
                self._append_with_retry(record_for_shard_map(shard_spec))
            if model_dir is not None:
                self._append_with_retry({
                    "kind": "swap",
                    "version": service.registry.version,
                    "previous_version": None,
                    "source_dir": str(model_dir)})

    def status(self) -> Dict[str, object]:
        with self._lock:
            out = {"role": "publisher", "failed": self._failed,
                   "appended": self._appended,
                   "pending_events": len(self._buffer),
                   "head_seq": None}
        if self.shard_spec is not None:
            out["shard_spec"] = self.shard_spec.to_dict()
        return out

    def shard_audit(self, shard_index: int) -> Dict[str, object]:
        """The publisher-side half of a per-shard audit: sha256 of its
        FULL tables' rows filtered to `shard_index`'s owned entities
        (GET /fleet/audit?shard=K).  A converged shard replica's
        `table_hashes()` reports the identical hashes, since its
        resident tables ARE that filtered slice."""
        if self.shard_spec is None:
            raise ValueError("this publisher has no shard spec "
                             "(cli.serve --shard-count)")
        scorer = self.service.registry.scorer
        return {"version_vector": self.service.version_vector(),
                "shard": {"index": int(shard_index),
                          **self.shard_spec.to_dict()},
                "table_hashes": scorer.shard_table_hashes(
                    self.shard_spec, int(shard_index))}

    # -- the ordered event -> record pump ------------------------------------

    def _on_event(self, ticket: int, event: dict) -> None:
        with self._lock:
            if self._failed is not None:
                return  # a broken log must not block serving
            self._buffer[ticket] = event
        # single-flusher: whoever finds the next expected ticket AND the
        # flusher slot free drains in ticket order; racing threads buffer
        # and leave — file order therefore always equals mutation order
        while True:
            with self._lock:
                if self._flushing or self._next not in self._buffer:
                    return
                self._flushing = True
                event = self._buffer.pop(self._next)
                self._next += 1
            try:
                self._append_with_retry(record_for_event(event))
            except Exception as e:
                msg = f"{type(e).__name__}: {e}"
                with self._lock:
                    self._failed = msg
                logger.error(
                    "replication publish FAILED (%s): the log is behind "
                    "the live model and replicas will stall — restart "
                    "the publisher against a repaired log", msg)
                telemetry.event("fleet_publish_failed", error=msg)
                return
            finally:
                with self._lock:
                    self._flushing = False

    def _append_with_retry(self, record: dict) -> int:
        attempt = 0
        while True:
            attempt += 1
            try:
                seq = self.log.append(record)
                with self._lock:
                    self._appended += 1
                return seq
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                if not faults.is_transient(e) or \
                        attempt >= self.max_attempts:
                    raise
                telemetry.event("fleet_append_retry", attempt=attempt,
                                error=f"{type(e).__name__}: {e}")
                time.sleep(self.backoff_s * (2 ** (attempt - 1))
                           * (1.0 + 0.25 * self._jitter.random()))

    def head_seq(self) -> int:
        return self.log.head_seq()


class Replica:
    """A follower ScoringService kept converged with the replication log.

    `join()` is the catch-up path (returns only when the replica is
    bit-identical with the log head and warmed); `start()` runs the
    background poll loop; `poll_once()` is one tail-apply cycle (tests
    and the bench drive it directly for determinism)."""

    def __init__(self, service, log: ReplicationLog, state_dir: str,
                 config: ReplicaConfig = ReplicaConfig()):
        self.service = service
        self.log = log
        self.state_dir = str(state_dir)
        self.config = config
        os.makedirs(self.state_dir, exist_ok=True)
        self._lock = locktrace.tracked(threading.Lock(), "Replica._lock")
        self._applied_seq = 0                    # photonlint: guarded-by=_lock
        self._head_seen = 0                      # photonlint: guarded-by=_lock
        self._ready = False                      # photonlint: guarded-by=_lock
        self._draining = False                   # photonlint: guarded-by=_lock
        self._failed: Optional[str] = None       # photonlint: guarded-by=_lock
        self._catchup_s: Optional[float] = None  # photonlint: guarded-by=_lock
        self._thread: Optional[threading.Thread] = None  # photonlint: guarded-by=_lock
        self._closed = threading.Event()
        self._jitter = random.Random(0xD0D0)
        # the replica's own fold of everything it applied (base model dir
        # + net changed rows): persisted WITH the applied seq, because a
        # restarted process rebuilds its tables from the base model and
        # a bare seq would skip the history that produced them.
        # Thread-confined by protocol, not locked: join() runs before
        # start(), and afterwards ONLY the apply path (loop thread or a
        # manual poll_once driver, never both) touches it.
        self._fold: Optional[_FoldState] = None  # photonlint: guarded-by=none

    # -- durable applied-seq + folded row state ------------------------------

    def _applied_path(self) -> str:
        return os.path.join(self.state_dir, _APPLIED_NAME)

    def _load_state(self):
        """-> (applied_seq, fold | None).  No durable fold (or a fold
        that could not track a record) forces a FULL replay from zero —
        correct, just slower than a resume."""
        path = self._applied_path()
        if not os.path.exists(path):
            return 0, None
        with open(path) as f:
            state = json.load(f)
        snap = state.get("snapshot")
        if not snap:
            return 0, None
        return int(state.get("applied_seq", 0)), \
            _FoldState.from_snapshot(snap)

    def _persist_applied(self, applied_seq: int) -> None:
        snap = None
        if self._fold is not None and self._fold.model_dir is not None:
            snap = self._fold.to_snapshot()
        durable.atomic_write_json(self._applied_path(), {
            "applied_seq": int(applied_seq),
            "snapshot": snap,
            "version_vector": self.service.registry.version_vector()})

    def _fold_record(self, env: dict) -> None:
        if self._fold is None:
            return
        try:
            self._fold.fold(env)
        except ReplicationLogError as e:
            # e.g. a full-model rollback whose previous version this
            # fold never saw: the fold can no longer mirror the live
            # state, so stop persisting it — restarts fall back to a
            # full replay instead of trusting a wrong snapshot
            logger.warning("replica fold disabled (%s): restarts will "
                           "replay the full log", e)
            self._fold = None

    # -- lifecycle -----------------------------------------------------------

    def join(self) -> Dict[str, object]:
        """Catch up to the log head and report ready: snapshot bootstrap
        (when the tail before our applied seq was compacted away), tail
        replay, delta-program warmup.  On a restart after a crash this
        resumes from the durably-recorded applied seq; replay is
        idempotent, so re-applying the record the crash interrupted is
        harmless and the tables converge bit-identically."""
        t0 = clock()
        applied, fold = self._load_state()
        self._fold = fold if fold is not None else _FoldState()
        resumed = applied > 0
        with telemetry.span("replica_join", resumed=resumed,
                            applied_seq=applied):
            bootstrapped = False
            if resumed:
                # restore the durable fold's table state onto the fresh
                # registry (the process restart threw the tables away)
                self._bootstrap(fold.to_snapshot())
                bootstrapped = True
            snap = self.log.latest_snapshot()
            if snap is not None and applied < int(snap["upto_seq"]):
                self._bootstrap(snap)
                self._fold = _FoldState.from_snapshot(snap)
                applied = int(snap["upto_seq"])
                bootstrapped = True
            applied, records = self._apply_tail(applied)
            self._persist_applied(applied)
            warmup_s = self.service.registry.scorer.warmup_delta(
                self.config.warm_delta_rows)
        catchup_s = clock() - t0
        with self._lock:
            self._applied_seq = applied
            self._head_seen = max(self._head_seen, applied)
            self._ready = True
            self._catchup_s = catchup_s
        self.service.metrics.observe_replica_ready(True, catchup_s)
        self.service.metrics.observe_replica_applied(
            applied_seq=applied, lag_seq=0, records=records)
        logger.info("replica ready: applied_seq=%d (%s, %d record(s) "
                    "replayed, catch-up %.3fs)", applied,
                    "resumed" if resumed else "fresh join", records,
                    catchup_s)
        return {"applied_seq": applied, "records_replayed": records,
                "resumed": resumed, "bootstrapped": bootstrapped,
                "catchup_s": catchup_s, "delta_warmup_s": warmup_s}

    def _bootstrap(self, snap: dict) -> None:
        """Fast-forward to a compaction snapshot: load its base model and
        scatter the folded net rows."""
        registry = self.service.registry
        with telemetry.span("replica_bootstrap",
                            upto_seq=int(snap["upto_seq"])):
            if registry.version != snap["version"]:
                registry.load(snap["model_dir"], version=snap["version"])
            restored = {
                lane: (decode_array(enc["rows"]),
                       decode_array(enc["values"]))
                for lane, enc in snap.get("restored", {}).items()}
            registry.replay_row_state(restored, snap["version"],
                                      int(snap["delta_seq"]))

    def _apply_tail(self, applied: int):
        """Apply every durable record past `applied`; returns (new
        applied seq, records applied)."""
        records = self._read_with_retry(applied)
        count = 0
        for env in records:
            self._apply_with_retry(env)
            self._fold_record(env)
            applied = int(env["log_seq"])
            count += 1
            now = time.time()
            # log-append -> replica-apply latency (the record envelope
            # carries its append wall time) + end-to-end feedback ->
            # fleet-visible latency for delta records whose trace names
            # the oldest intake time
            self.service.metrics.observe_replica_record(
                apply_latency_s=max(now - float(env.get("t", now)), 0.0),
                feedback_visible_s=self._feedback_visible_s(env, now))
            if count % max(self.config.ack_every, 1) == 0:
                self._persist_applied(applied)
        with self._lock:
            if records:
                self._head_seen = max(self._head_seen,
                                      int(records[-1]["log_seq"]))
        return applied, count

    @staticmethod
    def _feedback_visible_s(env: dict, now: float):
        trace = env["record"].get("trace") or {}
        oldest = trace.get("enqueued_wall_s")
        if env["record"].get("kind") != "delta" or not oldest:
            return None
        return max(now - float(oldest), 0.0)

    def poll_once(self) -> int:
        """One tail-apply cycle (the poll loop's body).  Returns the
        number of records applied; 0 while draining/failed."""
        with self._lock:
            if self._draining or self._failed is not None:
                return 0
            applied = self._applied_seq
        try:
            new_applied, count = self._apply_tail(applied)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:
            msg = f"{type(e).__name__}: {e}"
            with self._lock:
                self._failed = msg
            self.service.metrics.observe_replica_ready(False)
            logger.error("replica apply FAILED (%s): marking this "
                         "replica failed — /healthz degrades and the "
                         "front stops routing here", msg)
            telemetry.event("replica_failed", error=msg)
            # the postmortem window is NOW: dump the flight ring before
            # the operator (or the orchestrator) restarts the process
            flight.trigger("replica.failed", error=msg)
            return 0
        if count:
            self._persist_applied(new_applied)
        with self._lock:
            self._applied_seq = new_applied
            self._head_seen = max(self._head_seen, new_applied)
            head = self._head_seen
        self.service.metrics.observe_replica_applied(
            applied_seq=new_applied, lag_seq=head - new_applied,
            records=count)
        return count

    def _read_with_retry(self, applied: int):
        cfg = self.config
        attempt = 0
        while True:
            attempt += 1
            try:
                return self.log.read(applied)
            except (KeyboardInterrupt, SystemExit):
                raise
            except ReplicationLogError:
                raise  # structural: gap/corruption/compaction, not transient
            except BaseException as e:
                if not faults.is_transient(e) or attempt >= cfg.max_attempts:
                    raise
                self.service.metrics.observe_replica_apply_retry()
                telemetry.event("replica_read_retry", attempt=attempt,
                                error=f"{type(e).__name__}: {e}")
                time.sleep(cfg.backoff_s * (2 ** (attempt - 1))
                           * (1.0 + 0.25 * self._jitter.random()))

    def _apply_with_retry(self, env: dict) -> None:
        cfg = self.config
        attempt = 0
        trace = env["record"].get("trace") or {}
        while True:
            attempt += 1
            try:
                with telemetry.span(
                        "replica_apply", seq=int(env["log_seq"]),
                        kind=env["record"]["kind"],
                        request_ids=",".join(
                            trace.get("request_ids") or ()),
                        remote_parent=trace.get("parent")):
                    self._apply_record(env)
                return
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                if not faults.is_transient(e) or attempt >= cfg.max_attempts:
                    raise
                self.service.metrics.observe_replica_apply_retry()
                telemetry.event("replica_apply_retry", attempt=attempt,
                                seq=int(env["log_seq"]),
                                error=f"{type(e).__name__}: {e}")
                time.sleep(cfg.backoff_s * (2 ** (attempt - 1))
                           * (1.0 + 0.25 * self._jitter.random()))

    def _apply_record(self, env: dict) -> str:
        """Replay ONE record through the local registry.  Every branch is
        idempotent (guarded on the version vector), so crash-replay of an
        already-applied record is a no-op — the property that makes the
        at-least-once applied-seq persistence safe."""
        rec = env["record"]
        kind = rec["kind"]
        faults.fire("replica.apply", kind=kind)
        registry = self.service.registry
        shard = getattr(registry.scorer, "shard", None)
        if shard is not None:
            # sharded catch-up fault site: fired INSIDE the apply retry
            # loop, so injected transients exercise the same backoff
            # discipline as any replicated apply; fatals mark the
            # replica failed exactly like replica.apply
            faults.fire("shard.catchup", shard=str(shard.index))
        if kind == "shard_map":
            if shard is None:
                return "skipped"  # full-model replica: owns everything
            from photon_ml_tpu.fleet.shards import ShardSpec
            try:
                spec = ShardSpec.from_dict(rec["spec"])
            except ValueError as e:
                raise ReplicaError(
                    f"shard_map record at seq {env['log_seq']} is "
                    f"unusable ({e})") from e
            if spec != shard.spec:
                raise ReplicaError(
                    f"shard_map record at seq {env['log_seq']} announces "
                    f"partition {spec.to_dict()} but this replica was "
                    f"built for {shard.spec.to_dict()} — a replica "
                    "cannot re-partition live; restart it with the "
                    "fleet's spec (cli.serve --shard K/N matching the "
                    "publisher's --shard-count)")
            return "applied"
        if kind == "swap":
            if registry.version == rec["version"]:
                return "skipped"  # same version: the join-time base model
            if not rec.get("source_dir"):
                raise ReplicaError(
                    f"swap record seq {env['log_seq']} has no model "
                    "directory (the publisher installed an in-memory "
                    "model) — replicas cannot replay it")
            registry.load(rec["source_dir"], version=rec["version"])
            return "applied"
        if kind == "delta":
            vv = registry.version_vector()
            if vv["version"] == rec["version"] and \
                    vv["delta_seq"] >= int(rec["delta_seq"]):
                return "skipped"  # crash-replay of an applied delta
            registry.apply_delta(delta_from_record(rec))
            return "applied"
        if kind == "delta_rollback":
            vv = registry.version_vector()
            if vv["version"] == rec["version"] and \
                    vv["delta_seq"] == int(rec["to_delta_seq"]) and \
                    registry.pending_deltas() == 0:
                return "skipped"
            restored = {lane: (decode_array(enc["rows"]),
                               decode_array(enc["values"]))
                        for lane, enc in rec["restored"].items()}
            registry.replay_row_state(restored, rec["version"],
                                      int(rec["to_delta_seq"]))
            return "applied"
        if kind == "rollback":
            if registry.version == rec["version"]:
                return "skipped"
            try:
                got = registry.rollback()
            except RuntimeError as e:
                raise ReplicaError(
                    f"cannot replay the full-model rollback at seq "
                    f"{env['log_seq']} ({e}): this process never held "
                    f"the previous version {rec['version']!r} in memory "
                    "— rejoin with a FRESH state directory so the whole "
                    "history replays") from e
            if got != rec["version"]:
                raise ReplicaError(
                    f"full-model rollback replay landed on {got!r} but "
                    f"the record (seq {env['log_seq']}) expects "
                    f"{rec['version']!r} — this replica's version "
                    "history diverged; rejoin from a snapshot")
            return "applied"
        raise ReplicaError(
            f"unknown record kind {kind!r} at seq {env['log_seq']} — "
            "this replica is older than the publisher; upgrade it")

    # -- status / audit ------------------------------------------------------

    def status(self) -> Dict[str, object]:
        with self._lock:
            out = {"role": "replica", "ready": self._ready,
                   "draining": self._draining, "failed": self._failed,
                   "applied_seq": self._applied_seq,
                   "lag_seq": max(self._head_seen - self._applied_seq, 0),
                   "catchup_s": (None if self._catchup_s is None
                                 else round(self._catchup_s, 3))}
        shard = self.service.registry.scorer.shard_info()
        if shard is not None:
            out["shard"] = shard
        return out

    def audit(self) -> Dict[str, object]:
        """Version vector + table hashes + applied seq: the convergence
        identity (GET /fleet/audit)."""
        out = self.service.audit()
        out.update(self.status())
        return out

    def healthy(self) -> bool:
        with self._lock:
            return self._ready and not self._draining \
                and self._failed is None

    # -- drain / background loop ---------------------------------------------

    def drain(self) -> Dict[str, object]:
        """Stop applying and flip /healthz to 503 so the front stops
        routing here; in-flight requests finish on the live scorer, then
        the process can detach."""
        with self._lock:
            already = self._draining
            self._draining = True
        if not already:
            self.service.metrics.observe_replica_ready(False)
            telemetry.event("replica_draining")
            logger.info("replica draining: new traffic refused, log "
                        "apply stopped")
        return self.status()

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._closed.clear()
            thread = threading.Thread(target=self._loop, daemon=True,
                                      name="photon-fleet-replica")
            self._thread = thread
        thread.start()

    def _loop(self) -> None:
        while not self._closed.is_set():
            self._closed.wait(timeout=self.config.poll_interval_s)
            if self._closed.is_set():
                break
            try:
                self.poll_once()
            except Exception as e:  # the loop must never die silently
                logger.exception("replica poll cycle failed: %s", e)

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout)
